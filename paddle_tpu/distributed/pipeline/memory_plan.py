"""Activation-memory planner for pipelined training (ISSUE 15).

The schedules bound activations structurally (1F1B: an S = min(M, 2P-1)
slot stash instead of GPipe's O(M) residuals); this module decides what
happens WITHIN that bound: which of a stage's layers keep their full VJP
residuals ("none"), which rematerialize from the block input ("remat"),
which push the saved input to the host tier ("offload"), and whether the
stash itself lives in host memory — all priced by
``cost_model.pipeline_cost`` against an (emulated) HBM budget, choosing
the cheapest-in-time assignment that fits.

The planner REFUSES infeasible configs with the priced reason instead of
letting XLA OOM deep inside a compile: ``plan_memory(...)`` returns a
``MemoryPlan`` whose ``feasible`` flag and ``reason`` string callers gate
on (``PipelineTrainStep`` raises the reason; bench prints it). The same
pricer with ``pipe_degree=1, microbatches=1`` prices the UNPIPELINED step
— how a too-big model is shown to not fit before the pipeline is brought
in (tests/test_memory_plan.py pins both directions).

Host offload is a memory-SPACE move, not an algorithm change: on TPU the
named space is "pinned_host" (distinct from HBM — real bytes saved); on
CPU the only space is "unpinned_host" which IS device memory, so
``host_offload_supported()`` reports False and the planner only selects
offload when the caller forces ``allow_offload=True`` (the CPU tests do,
to exercise the lowering; the bytes claim is only made on TPU).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from ...cost_model import pipeline_cost

__all__ = ["MemoryPlan", "plan_memory", "host_offload_supported",
           "gpt_activation_estimate", "plan_for_gpt"]


def host_offload_supported() -> bool:
    """True when the backend exposes a host memory space DISTINCT from
    device memory (TPU: "pinned_host" next to "device"). On CPU the
    default space is already host memory, so there is nothing to offload
    TO — the planner must not claim bytes it cannot move."""
    try:
        import jax

        dev = jax.devices()[0]
        kinds = {m.kind for m in dev.addressable_memories()}
        return ("pinned_host" in kinds
                and dev.default_memory().kind != "pinned_host")
    except Exception:
        return False


def _offload_kind() -> str:
    """The memory-space name the offload tier lowers to: the real host
    space when one exists, else the CPU default space (an exercisable
    no-op — see module docstring)."""
    return "pinned_host" if host_offload_supported() else "unpinned_host"


@dataclasses.dataclass(frozen=True)
class MemoryPlan:
    """One planner verdict: the per-layer policy vector for a stage, the
    stash placement, the priced cost account, and the feasibility gate."""

    policies: Tuple[str, ...]           # per layer of ONE stage
    stash_offload: bool
    stash_memory_kind: Optional[str]    # None = stash stays in HBM
    pipe_degree: int
    microbatches: int
    feasible: bool
    reason: str                         # priced explanation either way
    cost: dict                          # pipeline_cost(...) account

    @property
    def activation_bytes_peak(self) -> int:
        return int(self.cost.get("activation_bytes_peak", 0))

    @property
    def bubble_fraction(self) -> float:
        return float(self.cost.get("bubble_fraction", 0.0))

    def describe(self) -> str:
        pol = ",".join(self.policies)
        return (f"MemoryPlan(P={self.pipe_degree}, M={self.microbatches}, "
                f"policies=[{pol}], stash_offload={self.stash_offload}, "
                f"feasible={self.feasible}: {self.reason})")


def plan_memory(*, num_layers: int, pipe_degree: int, microbatches: int,
                activation_bytes_per_layer: float,
                input_bytes_per_layer: float,
                layer_flops: float,
                fixed_bytes: float = 0.0,
                hbm_budget_bytes: Optional[float] = None,
                device_kind: str = "cpu",
                allow_offload: Optional[bool] = None,
                host_bandwidth_bps: Optional[float] = None,
                ) -> MemoryPlan:
    """Choose the cheapest-in-time per-layer remat/offload assignment (and
    stash placement) that fits ``hbm_budget_bytes``.

    The stage's layers are homogeneous, so an assignment is fully
    described by (k_offload, k_remat): that many layers at "offload" /
    "remat", the rest "none" — the planner enumerates the O(L^2) frontier,
    prices each with ``cost_model.pipeline_cost`` (each offloaded input
    crosses the host link twice per micro-batch; each remat'd layer costs
    one extra layer-forward), and keeps the fitting assignment with the
    lowest ``time_lower_bound_s``. Without a budget the all-"none" plan
    wins by construction. Returns an INFEASIBLE plan (never raises) when
    even full offload is over budget — ``reason`` carries the priced gap.

    ``allow_offload`` defaults to :func:`host_offload_supported` — on CPU
    the offload tier saves nothing, so the planner does not pretend.
    """
    L_total = int(num_layers)
    P = int(pipe_degree)
    if L_total % P:
        raise ValueError(
            f"num_layers={L_total} not divisible by pipe_degree={P}")
    L = L_total // P
    if allow_offload is None:
        allow_offload = host_offload_supported()
    kw = dict(pipe_degree=P, microbatches=int(microbatches),
              layers_per_stage=L,
              activation_bytes_per_layer=float(activation_bytes_per_layer),
              input_bytes_per_layer=float(input_bytes_per_layer),
              layer_flops=float(layer_flops),
              fixed_bytes=float(fixed_bytes),
              hbm_budget_bytes=hbm_budget_bytes,
              device_kind=device_kind)
    if host_bandwidth_bps is not None:
        kw["host_bandwidth_bps"] = float(host_bandwidth_bps)

    def price(k_off: int, k_rem: int, stash_off: bool) -> dict:
        pol = (["offload"] * k_off + ["remat"] * k_rem
               + ["none"] * (L - k_off - k_rem))
        return pipeline_cost(policies=pol, stash_offload=stash_off, **kw)

    def make(cost: dict, feasible: bool, reason: str) -> MemoryPlan:
        stash_off = bool(cost["stash_offload"])
        return MemoryPlan(
            policies=tuple(cost["policies"]),
            stash_offload=stash_off,
            stash_memory_kind=_offload_kind() if stash_off else None,
            pipe_degree=P, microbatches=int(microbatches),
            feasible=feasible, reason=reason, cost=cost)

    if hbm_budget_bytes is None:
        cost = price(0, 0, False)
        return make(cost, True, "no HBM budget given: all-\"none\" plan "
                                "(cheapest in time)")

    best = None
    stash_options = (False, True) if allow_offload else (False,)
    max_off = L if allow_offload else 0
    for stash_off in stash_options:
        for k_off in range(max_off + 1):
            for k_rem in range(L - k_off + 1):
                c = price(k_off, k_rem, stash_off)
                if not c["fits"]:
                    continue
                if best is None or (c["time_lower_bound_s"]
                                    < best["time_lower_bound_s"]):
                    best = c
    if best is not None:
        return make(best, True, best["why"])
    # nothing fits: report the priced gap of the most aggressive plan
    worst_case = price(max_off, L - max_off, bool(allow_offload and
                                                  stash_options[-1]))
    return make(worst_case, False,
                f"no assignment fits: even the most aggressive plan "
                f"({worst_case['why']})"
                + ("" if allow_offload else
                   "; host offload unavailable on this backend"))


# --------------------------------------------------------------- gpt glue

def gpt_activation_estimate(cfg, microbatch_size: int,
                            seq: Optional[int] = None,
                            mesh=None) -> dict:
    """Per-DEVICE activation byte/FLOP estimates for one gpt block on one
    micro-batch — the numbers ``plan_memory`` prices.

    ``activation_bytes_per_layer`` counts the VJP residuals one block keeps
    under policy "none": the block input, both LN outputs, qkv, the
    attention output, and the two MLP intermediates (~10h + 2f floats per
    token), plus the [n, s, s] softmax probabilities when the non-flash
    path runs. ``input_bytes_per_layer`` is the one [mb, s, h] block input
    "remat" keeps. Both divide by the tensor/sequence-parallel degrees the
    mesh actually shards over (the 'model' axis slices qkv/mlp widths,
    'sep' slices the sequence dim).
    """
    import numpy as np

    from ...framework import dtype as dtype_mod

    s = int(seq or cfg.max_position_embeddings)
    mb = int(microbatch_size)
    h, f, n = cfg.hidden_size, cfg.ffn, cfg.num_heads
    itemsize = np.dtype(dtype_mod.convert_dtype(cfg.dtype)).itemsize
    mp = sep = 1
    if mesh is not None:
        mp = int(mesh.shape.get("model", 1)) if "model" in mesh.axis_names \
            else 1
        sep = int(mesh.shape.get("sep", 1)) if "sep" in mesh.axis_names \
            else 1
    tok = mb * (s // sep)
    # widths sharded over 'model': qkv (3h), attn out (h), mlp (2f)
    act = tok * itemsize * (6 * h + (4 * h + 2 * f) / mp)
    flash = bool(cfg.use_flash_attention and cfg.attn_dropout == 0.0)
    if not flash:
        act += mb * (n / mp) * (s // sep) * s * 4      # fp32 softmax probs
    inp = tok * itemsize * h
    # ~6 matmuls of [tok, h]x[h, ~h..f]: 2*tok*(3h^2 + h^2 + 2*h*f) flops
    flops = 2.0 * tok * (4.0 * h * h + 2.0 * h * f) / mp \
        + 4.0 * mb * (n / mp) * (s // sep) * s * cfg.head_dim
    return {
        "activation_bytes_per_layer": float(act),
        "input_bytes_per_layer": float(inp),
        "layer_flops": float(flops),
    }


def plan_for_gpt(cfg, *, pipe_degree: int, microbatches: int,
                 global_batch: int, seq: Optional[int] = None,
                 hbm_budget_bytes: Optional[float] = None,
                 mesh=None, fixed_bytes: float = 0.0,
                 allow_offload: Optional[bool] = None,
                 device_kind: str = "cpu") -> MemoryPlan:
    """``plan_memory`` over a GPTConfig: derives the per-layer byte/FLOP
    estimates from the config and the mesh's sharding degrees, with the
    micro-batch size taken from ``global_batch / microbatches`` divided by
    the mesh's data-parallel degree (the per-device slice the schedule
    actually stashes)."""
    M = int(microbatches)
    if int(global_batch) % M:
        raise ValueError(
            f"global_batch={global_batch} not divisible by M={M}")
    mb = int(global_batch) // M
    if mesh is not None:
        for ax in ("data", "sharding"):
            if ax in mesh.axis_names:
                mb = max(1, mb // int(mesh.shape[ax]))
    est = gpt_activation_estimate(cfg, mb, seq, mesh)
    return plan_memory(
        num_layers=cfg.num_layers, pipe_degree=int(pipe_degree),
        microbatches=M, fixed_bytes=fixed_bytes,
        hbm_budget_bytes=hbm_budget_bytes,
        allow_offload=allow_offload, device_kind=device_kind, **est)

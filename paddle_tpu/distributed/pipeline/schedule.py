"""SPMD pipeline parallelism — the real micro-batch schedules.

Reference capability: 1F1B with micro-batch overlap
(fleet/meta_parallel/pipeline_parallel.py:80-150 interleaving fwd/bwd,
pp_utils/p2p_communication.py:216-434 p2p send/recv between stage ranks,
static-graph SectionWorker paddle/fluid/framework/section_worker.cc:143-199).

TWO schedules, both collective-permute pipelines inside ONE SPMD program:

- `pipeline_spmd` — forward-only wave; training differentiates through it
  (GPipe fill-drain: AD keeps every micro-batch's residuals alive, O(M)
  activation memory). The simple/composable building block.
- `pipeline_1f1b` — the genuine 1F1B TRAIN step: forward and
  recompute-backward waves interleaved tick-by-tick with a
  min(M, 2P-1)-slot input stash, activation memory bounded by pipeline
  depth (the property the reference's schedule exists for). See its
  docstring for the wave arithmetic.

pipeline_spmd design notes:

- every pipe rank holds its stage's parameter slice (leading stacked-layer dim
  sharded over the 'pipe' mesh axis);
- micro-batches rotate through the stages with lax.ppermute: at step t, stage
  s computes micro-batch (t - s) — all stages busy in steady state, the same
  concurrency 1F1B achieves with p2p ranks;
- the loop runs M + P - 1 steps (bubble fraction (P-1)/(M+P-1), identical to
  GPipe fill/drain), with XLA overlapping each ppermute with the next step's
  compute (ICI transfer hides behind MXU work);
- backward is the TRANSPOSED pipeline: jax AD differentiates through scan +
  ppermute, yielding the reverse schedule for free — the part the reference
  spends p2p_communication.py hand-coding;
- inside the manual region tensor parallelism is explicit Megatron
  (column/row-sharded matmuls + psum over 'model') and sequence parallelism
  is the ring-attention body over 'sep' — the composition the reference
  builds from three separate communicator rings.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import mesh as mesh_mod


def _to_memory_kind(v, kind: Optional[str]):
    """Transfer `v` to a named memory space inside the trace (no-op when
    kind is None). The stash's host-offload tier rides this: on TPU
    `kind="pinned_host"` keeps the S input slots out of HBM between their
    forward write and backward read; on CPU the only space is
    "unpinned_host" (== device memory), so the path is exercisable but
    buys no bytes — memory_plan.host_offload_supported() tells the
    planner which regime it is pricing."""
    if kind is None:
        return v
    from jax._src.sharding_impls import TransferToMemoryKind

    return jax.device_put(v, TransferToMemoryKind(kind))


def pipeline_spmd(
    stage_fn: Callable,
    params,
    x,
    *,
    mesh,
    param_specs,
    pipe_axis: str = "pipe",
    microbatches: Optional[int] = None,
    batch_axes: Sequence[str] = ("data", "sharding"),
    seq_axis: str = "sep",
):
    """Run `x` through a pipeline of P = mesh.shape[pipe_axis] stages.

    stage_fn(local_params, x_mb) -> y_mb applies ONE stage's layers (the
    caller scans its local layer slices). `params` is a tuple of stacked
    arrays whose leading dim is sharded over `pipe_axis` (param_specs gives
    each one's full PartitionSpec INCLUDING the leading pipe dim). x is the
    full global batch [b, ...]; it is split into `microbatches` equal
    micro-batches along dim 0 (default: the pipe degree, the minimum that
    fills the pipeline).
    """
    P_deg = int(mesh.shape[pipe_axis])
    M = int(microbatches or P_deg)
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible by {M} micro-batches")
    mb = b // M
    x_mb = x.reshape(M, mb, *x.shape[1:])

    batch_tuple = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    seq = seq_axis if seq_axis in mesh.axis_names else None
    # [M, mb, s, ...]: micro dim unsharded, batch over dp axes, seq over sp
    x_spec = P(None, batch_tuple, seq, *([None] * (x.ndim - 2)))

    def body(params_local, xl):
        stage = jax.lax.axis_index(pipe_axis)
        T = M + P_deg - 1
        perm = [(i, (i + 1) % P_deg) for i in range(P_deg)]
        state0 = jnp.zeros(xl.shape[1:], xl.dtype)
        out0 = jnp.zeros_like(xl)

        def step(carry, t):
            state, outs = carry
            # fill: stage 0 ingests micro-batch t (clipped during drain)
            fresh = jax.lax.dynamic_index_in_dim(
                xl, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            state = jnp.where(stage == 0, fresh, state)
            y = stage_fn(params_local, state)
            # drain: micro-batch (t - P + 1) leaves the last stage at step t
            oi = t - (P_deg - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                outs, y.astype(outs.dtype), jnp.clip(oi, 0, M - 1), 0)
            outs = jnp.where(oi >= 0, upd, outs)
            # hand-off: stage s -> s+1 (wrap to 0 is overwritten by ingest)
            state = jax.lax.ppermute(y, pipe_axis, perm)
            return (state, outs), None

        (_, outs), _ = jax.lax.scan(step, (state0, out0), jnp.arange(T))
        # results live on the last stage; replicate over the pipe axis so the
        # (SPMD-replicated) head/loss can proceed on every rank
        outs = jnp.where(stage == P_deg - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, pipe_axis)

    out_mb = mesh_mod.compat_shard_map(
        body, mesh, (tuple(param_specs), x_spec), x_spec,
    )(tuple(params), x_mb)
    return out_mb.reshape(b, *x.shape[1:])


def _mb_spec(arr_ndim, batch_tuple, seq):
    """[M, mb, (seq), ...] PartitionSpec: micro dim unsharded, batch over the
    dp axes, (optional) sequence dim over sp."""
    dims = [None, batch_tuple]
    if arr_ndim >= 3:
        dims.append(seq)
    dims += [None] * (arr_ndim - len(dims))
    return P(*dims)


def _spec_axes(spec):
    """Set of mesh axis names appearing in a PartitionSpec."""
    out = set()
    for entry in (spec or ()):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.update(entry)
        else:
            out.add(entry)
    return out


def pipeline_1f1b(
    embed_fn: Callable,
    stage_fn: Callable,
    loss_fn: Callable,
    params,
    x,
    labels,
    *,
    mesh,
    param_specs,
    pipe_axis: str = "pipe",
    microbatches: Optional[int] = None,
    batch_axes: Sequence[str] = ("data", "sharding"),
    seq_axis: str = "sep",
    natural_axes: Sequence[str] = ("model",),
    grad_sync: Optional[Callable] = None,
    sync_axes: Sequence[str] = (),
    sync_state: Sequence = (),
    sync_state_specs: Sequence = (),
    stash_memory_kind: Optional[str] = None,
):
    """Memory-bounded 1F1B pipeline TRAIN step: returns (loss, grads).

    Reference capability: the 1F1B schedule of
    fleet/meta_parallel/pipeline_parallel.py:80-150 (interleaved
    forward_backward_pipeline) and the static-graph SectionWorker
    (paddle/fluid/framework/section_worker.cc:143-199), whose point is that
    live activations are bounded by the pipeline depth P, not the
    micro-batch count M.

    TPU-native redesign — ONE SPMD scan over T = M + 2P - 1 lockstep ticks;
    the backward is hand-scheduled INSIDE the scan (no AD-of-scan residuals):

    - tick t, stage s forwards micro-batch  f = t - s            (wave down)
    - tick t, stage s backwards micro-batch b = t - (2P-1) + s   (wave up)
    - activations stashed per stage in a circular buffer of
      S = min(M, 2P-1) stage-INPUT slots — the O(P) 1F1B memory bound; the
      stage body is recomputed during the backward tick (the recompute policy
      the reference applies at scale anyway), so no other residual survives
      between ticks.
    - the backward tick takes jax.value_and_grad of a local objective
      `vdot(y, g_in)` (mid stages) or `loss_fn` (last stage, via lax.cond so
      the loss head only runs there), which yields d/d(params) and
      d/d(input) in one pass; input-grads ride the reverse ppermute.

    embed_fn(params, x_mb_raw) -> h   applied on stage 0 only (recomputed in
                                      that stage's backward ticks, so its
                                      param grads flow);
    stage_fn(params, h) -> h          one stage's blocks (P stages SPMD; pipe-
                                      stacked weights arrive pre-sliced);
    loss_fn(params, h, labels_mb) -> scalar mean loss of one micro-batch
                                      (applied on the last stage only).

    `params` is ONE pytree shared by all three fns — a weight used by both
    embed_fn and loss_fn (tied embedding) accumulates both contributions via
    the cross-stage psum. Grads are returned in float32, scaled to the mean
    over micro-batches; params sharded over `pipe_axis`/'model' stay sharded,
    everything else is reduced to replicated.

    Composition seams (ISSUE 15, consumed by PipelineTrainStep):

    - ``grad_sync(grads, state) -> (grads, new_state)`` replaces the default
      pmean over ``sync_axes`` (a subset of the batch axes): it runs INSIDE
      the shard_map body, after the pipe/sep reductions, with the grads
      still varying over ``sync_axes`` — the hook point where the quantized
      grad_comm bucket codecs reduce the data-axis wire in-trace.
      ``sync_state`` / ``sync_state_specs`` thread its carried state (the
      per-rank error-feedback residuals) through the body; the call then
      returns ``(loss, grads, *new_state)``.
    - ``stash_memory_kind`` places the S-slot input stash in a named memory
      space ("pinned_host" on TPU = the host-offload tier for the one
      per-stage activation buffer 1F1B keeps; None = HBM as before).
    """
    P_deg = int(mesh.shape[pipe_axis])
    M = int(microbatches or P_deg)
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible by {M} micro-batches")
    mb = b // M
    x_mb = x.reshape(M, mb, *x.shape[1:])
    lbl_mb = labels.reshape(M, mb, *labels.shape[1:])
    S = min(M, 2 * P_deg - 1)
    T = M + 2 * P_deg - 2  # last tick index is T; loop runs T+1 ticks

    batch_tuple = tuple(a for a in batch_axes if a in mesh.axis_names) or None
    seq = seq_axis if seq_axis in mesh.axis_names else None
    x_spec = _mb_spec(x_mb.ndim, batch_tuple, seq)
    l_spec = _mb_spec(lbl_mb.ndim, batch_tuple, seq)
    mesh_axes = set(mesh.axis_names)
    # axes grad_sync reduces itself (in-trace codec collectives); the
    # default pmean skips them so the hook sees per-rank partial grads
    sync_set = (set(a for a in sync_axes if a in mesh_axes)
                if grad_sync is not None else set())
    # memory space a consumed stash slot returns to (None = no transfer;
    # on CPU device memory IS "unpinned_host", so the emulated offload
    # path skips the identity round trip)
    fetch_kind = None
    if stash_memory_kind is not None:
        try:
            dev_kind = jax.devices()[0].default_memory().kind
        except Exception:
            dev_kind = "device"
        if dev_kind != stash_memory_kind:
            fetch_kind = dev_kind

    def body(params_in, xl, ll, *state):
        stage = jax.lax.axis_index(pipe_axis)
        is_first = stage == 0
        is_last = stage == P_deg - 1
        perm_fwd = [(i, (i + 1) % P_deg) for i in range(P_deg)]
        perm_bwd = [(i, (i - 1) % P_deg) for i in range(P_deg)]

        # Axes handled by vma-typed AD *inside* the per-tick VJP (the TP
        # axis: stage_fn's own psum points make JAX insert the correct
        # Megatron backward collectives there). Everything else is pre-cast
        # to device-varying BEFORE differentiation, for two reasons:
        # - the transpose of an implicit replicated->varying cast is a psum,
        #   and the VJP below runs under a lax.cond whose predicate differs
        #   across pipe ranks — a pipe-psum materializing inside those
        #   branches is a mismatched collective (observed as an XLA CPU
        #   AllReduce abort);
        # - for the batch axes it would all-reduce the full parameter grads
        #   every tick; per-rank partials reduced once after the scan ride a
        #   single collective instead.
        cast_axes = tuple(a for a in mesh.axis_names if a not in natural_axes)
        has_vma = hasattr(jax, "typeof")  # pre-vma jax has no typing to cast

        def to_varying(a, axes=cast_axes):
            if not has_vma:
                return a
            have = set(jax.typeof(a).vma)
            need = tuple(ax for ax in axes if ax not in have)
            return jax.lax.pcast(a, need, to="varying") if need else a

        params_local = jax.tree.map(to_varying, params_in)

        # local activation template from the embed output
        h_tpl = jax.eval_shape(lambda p, r: embed_fn(p, r), params_local,
                               jax.eval_shape(lambda a: a[0], xl))
        h_zero = jnp.zeros(h_tpl.shape, h_tpl.dtype)

        def apply_in(p, raw, h_in):
            """Stage input: stage 0 embeds the raw micro-batch, others take
            the ppermuted activation. where() keeps it one trace; the unused
            branch's grads are zeroed by the select."""
            h_emb = embed_fn(p, raw)
            return jnp.where(is_first, h_emb, h_in)

        g0 = {
            "state": h_zero,
            "gstate": jnp.zeros(h_tpl.shape, jnp.float32),
            "stash": _to_memory_kind(
                jnp.zeros((S,) + tuple(h_tpl.shape), h_tpl.dtype),
                stash_memory_kind),
            "grads": jax.tree.map(
                lambda a: jnp.zeros(a.shape, jnp.float32), params_local),
            "loss": jnp.zeros((), jnp.float32),
        }

        def tick(carry, t, do_fwd=True, do_bwd=True):
            """One lockstep tick. do_fwd/do_bwd are PYTHON constants: the
            fill ticks (t < P) have globally no backward work and the
            drain ticks (t > M+P-2) no forward work, so the caller scans
            three specialized bodies — fwd-only fill, fwd+bwd steady,
            bwd-only drain — instead of paying both phases on all
            M+2P-1 ticks. That cuts schedule cost from 4(M+2P-1) to
            4(M+P-1)-ish work units, at or below GPipe fill-drain's,
            while keeping the O(P) stash (see tools/pipeline_throughput.py
            for the measured accounting)."""
            fwd_m = t - stage
            bwd_m = t - (2 * P_deg - 1 - stage)
            fwd_on = (fwd_m >= 0) & (fwd_m < M)
            bwd_on = (bwd_m >= 0) & (bwd_m < M)

            state_next = carry["state"]
            stash = carry["stash"]
            gstate_next = carry["gstate"]
            grads = carry["grads"]
            loss = carry["loss"]

            if do_fwd:
                # ---- forward: micro-batch fwd_m ----
                raw_f = jax.lax.dynamic_index_in_dim(
                    xl, jnp.clip(fwd_m, 0, M - 1), 0, keepdims=False)
                x_in = apply_in(params_local, raw_f, carry["state"])
                # offload tier: the slot VALUE crosses to the stash's
                # memory space before the update, so the S-slot buffer
                # never round-trips through device memory whole
                x_slot = _to_memory_kind(x_in.astype(carry["stash"].dtype),
                                         stash_memory_kind)
                stash = jnp.where(
                    fwd_on,
                    jax.lax.dynamic_update_index_in_dim(
                        carry["stash"], x_slot,
                        jnp.clip(fwd_m, 0, M - 1) % S, 0),
                    carry["stash"])
                y = stage_fn(params_local, x_in)
                state_next = jax.lax.ppermute(y.astype(h_tpl.dtype),
                                              pipe_axis, perm_fwd)

            if do_bwd:
                # ---- backward: micro-batch bwd_m (recompute + local VJP) ----
                raw_b = jax.lax.dynamic_index_in_dim(
                    xl, jnp.clip(bwd_m, 0, M - 1), 0, keepdims=False)
                lbl_b = jax.lax.dynamic_index_in_dim(
                    ll, jnp.clip(bwd_m, 0, M - 1), 0, keepdims=False)
                stash_x = jax.lax.dynamic_index_in_dim(
                    carry["stash"], jnp.clip(bwd_m, 0, M - 1) % S, 0,
                    keepdims=False)
                # offload tier: only the ONE slot being consumed returns
                # to device memory for the recompute
                stash_x = _to_memory_kind(stash_x, fetch_kind)

                def obj(p, h_stash, g_in):
                    xin = apply_in(p, raw_b, h_stash)
                    yb = stage_fn(p, xin)
                    return jax.lax.cond(
                        is_last,
                        lambda: loss_fn(p, yb, lbl_b).astype(jnp.float32),
                        lambda: jnp.vdot(yb.astype(jnp.float32), g_in),
                    )

                val, (dp, dx, _) = jax.value_and_grad(obj, argnums=(0, 1, 2))(
                    params_local, stash_x, carry["gstate"])
                grads = jax.tree.map(
                    lambda acc, g:
                        acc + jnp.where(bwd_on, g, 0.0).astype(acc.dtype),
                    carry["grads"], dp)
                loss = carry["loss"] + jnp.where(bwd_on & is_last, val, 0.0)
                gstate_next = jax.lax.ppermute(
                    jnp.where(bwd_on, dx.astype(jnp.float32), 0.0),
                    pipe_axis, perm_bwd)

            return {"state": state_next, "gstate": gstate_next,
                    "stash": stash, "grads": grads, "loss": loss}, None

        # lax.scan needs carry input and output vma types to agree; the
        # loop's fixed point depends on what stage_fn does (ppermute makes
        # values pipe-varying, a TP psum makes them model-replicated, the
        # sharded micro-batch data makes them batch-varying). Iterate
        # abstractly to the fixed point and pcast the zeros init up to it.
        # (Pre-vma jax carries no such types — nothing to converge.)
        if has_vma:
            for _ in range(len(mesh.axis_names) + 2):
                out_t = jax.eval_shape(lambda c: tick(c, jnp.int32(0))[0], g0)
                tgt = jax.tree.map(lambda o: frozenset(o.vma), out_t)
                cur = jax.tree.map(
                    lambda a: frozenset(jax.typeof(a).vma), g0)
                if tgt == cur:
                    break
                g0 = jax.tree.map(
                    lambda a, o: to_varying(a, tuple(sorted(o))), g0, tgt)
            else:
                raise ValueError("1F1B carry vma types did not converge")

        # Three specialized segments (identical math to one full scan —
        # the skipped phase is exactly the one whose work every stage
        # masks to zero on those ticks):
        #   fill  t in [0, P-1]:        no stage has backward work yet
        #   steady t in [P, M+P-2]:     both waves live (M-1 ticks)
        #   drain t in [M+P-1, M+2P-2]: forward wave fully retired
        carry, _ = jax.lax.scan(
            lambda c, t: tick(c, t, do_bwd=False), g0, jnp.arange(P_deg))
        if M > 1:
            carry, _ = jax.lax.scan(
                tick, carry, jnp.arange(P_deg, M + P_deg - 1))
        final, _ = jax.lax.scan(
            lambda c, t: tick(c, t, do_fwd=False), carry,
            jnp.arange(M + P_deg - 1, T + 1))

        inv_m = np.float32(1.0 / M)

        def reduce_out(g, owned):
            """One cross-rank reduction per value: psum over pipe (only the
            owning stage produced a non-zero), pmean over every other
            still-varying axis the value is not intentionally sharded on.
            Without vma typing (pre-vma jax) reduce unconditionally: psum
            over pipe is exact (non-owning stages masked their contribution
            to zero) and pmean over an already-replicated axis is the
            identity value-wise."""
            def _vma(a):
                return (set(jax.typeof(a).vma) if has_vma
                        else set(mesh.axis_names))
            if pipe_axis not in owned and pipe_axis in _vma(g):
                g = jax.lax.psum(g, pipe_axis)
            for ax in sorted(mesh_axes - owned - {pipe_axis}):
                if int(mesh.shape[ax]) > 1 and ax in _vma(g):
                    g = jax.lax.pmean(g, ax)
            return g

        loss = reduce_out(final["loss"] * inv_m, set())
        # grad_sync owns sync_set: the default reduction leaves those axes
        # varying (per-rank partial grads) for the hook's codec collectives
        grads = jax.tree.map(
            lambda g, spec: reduce_out(g * inv_m,
                                       _spec_axes(spec) | sync_set),
            final["grads"], param_specs)
        if grad_sync is not None:
            grads, new_state = grad_sync(grads, state)
            return (loss, grads) + tuple(new_state)
        return loss, grads

    # check_vma=True: with replication tracking on, the transpose of the TP
    # psum inside stage_fn is the (correct) identity pass-through — under
    # check_vma=False it would re-psum the already-replicated cotangent and
    # double every tensor-parallel gradient. Pre-vma jax cannot express that
    # pass-through (measured: TP grads come back exactly model_degree-fold),
    # so TP x 1F1B is refused loudly there; pure-pipe meshes are exact.
    if not hasattr(jax, "typeof") and int(
            mesh.shape.get("model", 1)) > 1:
        raise NotImplementedError(
            "1F1B with tensor parallelism needs vma-typed shard_map "
            "(jax >= 0.6); this jax would silently double TP gradients. "
            "Use the GSPMD fill-drain schedule or a pure-pipe mesh.")
    in_specs = (param_specs, x_spec, l_spec) + tuple(sync_state_specs)
    out_specs = (P(), param_specs) + tuple(sync_state_specs)
    out = mesh_mod.compat_shard_map(
        body, mesh, in_specs, out_specs, check=True,
    )(params, x_mb, lbl_mb, *sync_state)
    if grad_sync is not None:
        return out[0], out[1], tuple(out[2:])
    return out

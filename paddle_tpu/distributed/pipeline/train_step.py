"""PipelineTrainStep — 1F1B as the loss+grad engine of ONE compiled step.

The seam this composes through existed since the 1F1B schedule landed
(``jit.TrainStep(grad_fn=)``) but nothing exercised it together with the
rest of the training stack. This class is that composition:

- the **1F1B schedule** (schedule.pipeline_1f1b) computes loss+grads
  inside the same compiled SPMD program that runs the optimizer update —
  activation memory bounded by pipeline depth, not micro-batch count;
- the **quantized grad_comm codecs** (PR 8) reduce the data-axis gradient
  wire in-trace *inside the schedule's shard_map body* (the ``grad_sync``
  seam), with per-rank error-feedback residuals carried in and out of the
  jitted step exactly like the unpipelined ``TrainStep(grad_comm=)`` path
  — checkpointable via ``grad_comm_communicator.state_dict()``;
- the **ZeRO-3 at-rest layout** (PR 9's open GSPMD follow-on): with
  ``zero3_stage_params=True`` the pipe-stacked block weights rest sharded
  over ('pipe', 'sharding') on the layer dim — 1/(P*Z) of the stack per
  rank, gathered per stage inside the body; the gather's AD transpose
  re-shards the grads, so the fp32 accumulators and optimizer moments
  stay 1/(P*Z) too;
- the **memory planner** (memory_plan.plan_memory) picks the per-layer
  remat/offload policies and the stash tier against an (emulated) HBM
  budget, and REFUSES an infeasible config with the priced reason before
  anything compiles.

Bubble accounting: the segmented schedule runs 4M + 4P - 4 stage-work
units per step against 4M useful ones — bubble = (P-1)/(M+P-1), exported
as the ``pipeline_bubble_pct`` gauge and by :meth:`report` (bench.py's
gpt JSON carries it; tools/bench_gate.py gates it).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...jit import TrainStep
from ...observability.metrics import get_registry
from .. import mesh as mesh_mod
from .memory_plan import MemoryPlan, plan_for_gpt

__all__ = ["PipelineTrainStep", "MemoryPlanInfeasible"]

_m_bubble = get_registry().gauge(
    "pipeline_bubble_pct",
    help="analytic 1F1B bubble share of the composed train step, percent")
_m_micro = get_registry().gauge(
    "pipeline_microbatches", help="micro-batch count of the composed step")
_m_stash = get_registry().gauge(
    "pipeline_stash_slots",
    help="1F1B input-stash slots (min(M, 2P-1)) of the composed step")


class MemoryPlanInfeasible(RuntimeError):
    """The planner found no remat/offload assignment under the budget;
    the message carries the priced reason (plan.describe())."""

    def __init__(self, plan: MemoryPlan):
        super().__init__(plan.reason)
        self.plan = plan


class _LocalParam:
    """Shape/dtype shim for the bucket planner: a bucket plan over the
    PER-RANK shard shapes (what the shard_map body actually reduces)."""

    __slots__ = ("_value",)

    def __init__(self, shape, dtype):
        self._value = jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def _local_shape(shape, spec, mesh):
    """Per-rank block shape of a global array under a PartitionSpec."""
    out = list(shape)
    for i, entry in enumerate(tuple(spec or ())):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        deg = 1
        for ax in axes:
            if ax in mesh.axis_names:
                deg *= int(mesh.shape[ax])
        out[i] = out[i] // deg
    return tuple(out)


class PipelineTrainStep(TrainStep):
    """One fused, compiled 1F1B-pipelined training step for scan-mode GPT.

        mesh_mod.set_mesh(build_mesh({"pipe": 4, "data": 2}))
        step = PipelineTrainStep(model, optimizer,
                                 grad_comm="int8_block",
                                 hbm_budget_bytes=2 << 30)
        loss = step(inputs=(ids,), labels=(lbls,))

    ``memory_plan``: "auto" (default) plans on the first call from the
    batch shape and ``hbm_budget_bytes`` (raising
    :class:`MemoryPlanInfeasible` with the priced reason when nothing
    fits); a :class:`MemoryPlan` pins an explicit plan; None defers to
    the model config's recompute/recompute_policy.
    """

    def __init__(self, model, optimizer, *, grad_comm=None,
                 memory_plan="auto", zero3_stage_params: bool = False,
                 hbm_budget_bytes: Optional[int] = None,
                 batch_spec=None, loss_fn=None):
        cfg = getattr(model, "config", None)
        if cfg is None or getattr(cfg, "mode", None) != "scan":
            raise ValueError(
                "PipelineTrainStep drives the scan-mode (pipe-stacked) "
                "GPT decoder; got a model without a scan-mode config")
        mesh = mesh_mod.get_mesh()
        if mesh is None or "pipe" not in mesh.axis_names \
                or int(mesh.shape["pipe"]) <= 1:
            raise ValueError(
                "PipelineTrainStep needs an active mesh with pipe "
                "degree > 1 (mesh_mod.set_mesh(build_mesh({'pipe': P, "
                "...})))")
        # the base ctor rejects grad_comm+grad_fn for the unpipelined DP
        # body; the pipeline grad_fn handles the codec reduction itself,
        # so attach grad_comm AFTER construction via the dedicated seam
        super().__init__(model, loss_fn, optimizer, batch_spec=batch_spec)
        if grad_comm is not None:
            from ..grad_comm import GradCommConfig, GradCommunicator

            if isinstance(grad_comm, str):
                grad_comm = GradCommConfig(codec=grad_comm)
            self._gc_comm = GradCommunicator(grad_comm)
        self._pipe_model = model
        self._pipe_cfg = cfg
        self._pipe_mesh = mesh
        self._plan_request = memory_plan
        self._zero3_request = bool(zero3_stage_params)
        self._hbm_budget = hbm_budget_bytes
        self.memory_plan: Optional[MemoryPlan] = (
            memory_plan if isinstance(memory_plan, MemoryPlan) else None)
        self._local_params = None          # bucket-plan shapes (per rank)
        self._gc_bucket_plan = None
        self._gc_bucket_axes = {}
        self._pipe_order = None
        self._pipe_specs = None
        self._prepared = False

    # ------------------------------------------------------ lazy assembly
    def _microbatches(self) -> int:
        return int(self._pipe_cfg.pp_microbatches
                   or self._pipe_mesh.shape["pipe"])

    def _prepare(self, inputs):
        """Build the memory plan + grad engine from the first batch's
        shape (the planner prices the actual micro-batch size)."""
        from ...models.gpt import gpt_1f1b_grad_fn

        mesh, cfg = self._pipe_mesh, self._pipe_cfg
        first = inputs[0]
        shape = getattr(first, "shape", None) or first._value.shape
        b, s = int(shape[0]), int(shape[1])
        M = self._microbatches()
        plan = self.memory_plan
        if plan is None and self._plan_request == "auto" \
                and self._hbm_budget is not None:
            plan = plan_for_gpt(
                cfg, pipe_degree=int(mesh.shape["pipe"]), microbatches=M,
                global_batch=b, seq=s,
                hbm_budget_bytes=self._hbm_budget, mesh=mesh)
            if not plan.feasible:
                raise MemoryPlanInfeasible(plan)
            self.memory_plan = plan

        # pass 1: the engine's layout (traversal order + at-rest specs) —
        # the bucket plan and residual shardings derive from it
        probe = gpt_1f1b_grad_fn(self._pipe_model, memory_plan=plan,
                                 zero3_stage_params=self._zero3_request)
        self._pipe_order = probe.order
        self._pipe_specs = probe.specs
        self._local_params = self._build_local_params()
        grad_sync, sync_specs = (None, ())
        if self._gc_comm is not None:
            grad_sync, sync_specs = self._build_grad_sync()
        if grad_sync is None:
            self.grad_fn = probe
        else:
            self.grad_fn = gpt_1f1b_grad_fn(
                self._pipe_model, memory_plan=plan,
                zero3_stage_params=self._zero3_request,
                grad_sync=grad_sync, sync_axes=("data",),
                sync_state_specs=sync_specs)
        if self.grad_fn.zero3_stage_params:
            # re-home the block weights (and thereby the grads, fp32
            # accumulators and optimizer moments) to the at-rest
            # ('pipe','sharding') layout — _shardings/_build read
            # dist_spec, so the whole compiled step agrees
            from ...models.gpt import _BLOCK_PARAMS

            dec = self._pipe_model.gpt.decoder
            for n in _BLOCK_PARAMS:
                getattr(dec, n).dist_spec = self.grad_fn.specs[n]
        P_deg = int(mesh.shape["pipe"])
        S = min(M, 2 * P_deg - 1)
        self._bubble_pct = 100.0 * (P_deg - 1) / (M + P_deg - 1)
        _m_bubble.set(self._bubble_pct)
        _m_micro.set(M)
        _m_stash.set(S)
        self._prepared = True

    def _build_local_params(self):
        """Per-rank shard shapes of every trainable param, in traversal
        order — what the in-body bucket plan is built over."""
        fm = self.fm
        mesh = self._pipe_mesh
        specs = self._pipe_specs
        order = self._pipe_order
        out = []
        ti = 0
        for p, m in zip(fm.params, fm.trainable_mask):
            if not m:
                continue
            spec = specs[order[ti]]
            out.append(_LocalParam(
                _local_shape(p._value.shape, spec, mesh), p._value.dtype))
            ti += 1
        return out

    # ------------------------------------------------- grad_comm plumbing
    def _gc_world(self, mesh):
        """The codec reduces over the DATA axis only: 'sharding' is either
        the ZeRO-3 at-rest dimension (owned, reduced by the gather's
        transpose) or handled by the schedule's default pmean."""
        if mesh is None or self._gc_comm is None:
            return (), 1
        if "data" in mesh.axis_names and mesh.shape["data"] > 1:
            return ("data",), int(mesh.shape["data"])
        return (), 1

    def _gc_buckets(self):
        """Bucket plan over the PER-RANK shard shapes, segregated by
        ownership signature: a flat bucket mixing a pipe-OWNED block
        grad (per-stage values) with a replicated embed/loss grad would
        make the whole bucket pipe-varying and break the replicated
        outputs' shard_map specs (and, on vma jax, their types). Params
        sharing a spec-axes set bucket together; indices renumber
        deterministically (same traversal on every rank)."""
        if self._gc_bucket_plan is not None:
            return self._gc_bucket_plan
        if self._local_params is None:
            raise RuntimeError("bucket plan requested before _prepare()")
        from ..grad_comm import build_buckets
        from .schedule import _spec_axes

        cfgc = self._gc_comm.config
        groups = {}
        for i, name in enumerate(self._pipe_order):
            key = tuple(sorted(_spec_axes(self._pipe_specs[name])))
            groups.setdefault(key, []).append(i)
        plan, plan_axes = [], {}
        for key in sorted(groups):
            idxs = groups[key]
            sub = [self._local_params[i] for i in idxs]
            for b in build_buckets(
                    sub, cfgc.comm_buffer_size, cfgc.last_comm_buffer_size,
                    dtypes=[np.dtype(p._value.dtype) for p in sub]):
                b.param_indices = [idxs[j] for j in b.param_indices]
                b.index = len(plan)
                plan.append(b)
                plan_axes[b.index] = frozenset(key)
        self._gc_bucket_plan = plan
        self._gc_bucket_axes = plan_axes
        return plan

    def _gc_res_layout(self, mesh):
        """Per-bucket residual stacking: a bucket of grads OWNED on some
        axes (the pipe-stacked block params; +'sharding' under ZeRO-3)
        has distinct values — and so a distinct quantization error — on
        every (owner x data) rank; a replicated-param bucket only differs
        per data rank. The residual spec mirrors exactly that, which is
        also what keeps the replicated grads' replication provable to
        shard_map after the error-feedback add."""
        out = []
        for b in self._gc_buckets():
            axes = tuple(ax for ax in mesh.axis_names
                         if (ax in self._gc_bucket_axes[b.index]
                             or ax == "data") and int(mesh.shape[ax]) > 1)
            rows = 1
            for ax in axes:
                rows *= int(mesh.shape[ax])
            out.append((rows, P(axes)))
        return out

    def _build_grad_sync(self):
        """The in-body quantized bucket reduction: flatten the per-rank
        grads bucket-wise, reduce each bucket with the configured codec
        over the data axis (the same ``reduce_bucket`` core every other
        path runs), thread the error-feedback residual rows through."""
        from .. import collective as _coll

        comm = self._gc_comm
        mesh = self._pipe_mesh
        axes, world = self._gc_world(mesh)
        if world <= 1:
            return None, ()
        if comm.group is None or tuple(comm.group.axes) != axes:
            comm.group = _coll.new_group(axes=axes)
        from ..grad_comm import EF_CODECS

        ef = (comm.config.error_feedback
              and comm.config.codec in EF_CODECS)
        order = self._pipe_order
        buckets = self._gc_buckets()

        def grad_sync(grads, state):
            flat_parts = [grads[k].reshape(-1) for k in order]
            new_state = list(state)
            for gi, b in enumerate(buckets):
                if len(b.param_indices) == 1:
                    flat = flat_parts[b.param_indices[0]]
                else:
                    flat = jnp.concatenate(
                        [flat_parts[pi] for pi in b.param_indices])
                residual = state[gi].reshape(-1) if ef else None
                reduced, nr, _w, _c = comm.reduce_bucket(
                    b, flat, world, residual=residual)
                if nr is not None:
                    new_state[gi] = nr.reshape(1, -1)
                for pi, off, n in zip(b.param_indices, b.offsets,
                                      b.numels):
                    flat_parts[pi] = reduced[off:off + n].astype(
                        flat_parts[pi].dtype)
            out = {k: fp.reshape(grads[k].shape)
                   for k, fp in zip(order, flat_parts)}
            return out, tuple(new_state)

        sync_specs = (tuple(spec for _rows, spec
                            in self._gc_res_layout(mesh))
                      if ef else ())
        return grad_sync, sync_specs

    # ------------------------------------------------------------- calls
    def __call__(self, inputs, labels=()):
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)
        if not self._prepared:
            self._prepare(inputs)
        return super().__call__(inputs, labels)

    def report(self) -> dict:
        """The pipeline account bench.py's gpt JSON carries: analytic
        bubble %, schedule geometry, the planner verdict, and the
        grad_comm wire stats of the newest step."""
        mesh = self._pipe_mesh
        M = self._microbatches()
        P_deg = int(mesh.shape["pipe"])
        out = {
            "pipe_degree": P_deg,
            "microbatches": M,
            "stash_slots": min(M, 2 * P_deg - 1),
            "pipeline_bubble_pct": round(
                100.0 * (P_deg - 1) / (M + P_deg - 1), 3),
            "zero3_stage_params": bool(
                getattr(self.grad_fn, "zero3_stage_params", False)),
        }
        if self.memory_plan is not None:
            out["memory_plan"] = {
                "policies": list(self.memory_plan.policies),
                "stash_offload": self.memory_plan.stash_offload,
                "feasible": self.memory_plan.feasible,
                "activation_bytes_peak":
                    self.memory_plan.activation_bytes_peak,
                "reason": self.memory_plan.reason,
            }
        if self.comm_stats:
            out["grad_comm"] = dict(self.comm_stats)
        return out

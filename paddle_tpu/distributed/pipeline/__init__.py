"""Pipeline parallelism as a first-class training path (ISSUE 15).

Layout:

- ``schedule.py``     — the SPMD micro-batch schedules (``pipeline_spmd``
                        fill-drain, ``pipeline_1f1b`` memory-bounded 1F1B),
                        unchanged surface from the seed-era
                        ``distributed/pipeline.py`` module this package
                        replaced, plus the in-schedule seams the training
                        path composes through (``grad_sync`` quantized
                        bucket reduction, host-offloaded stash tier).
- ``memory_plan.py``  — the activation-memory planner: per-layer
                        remat/offload policies priced by
                        ``cost_model.pipeline_cost`` against an (emulated)
                        HBM budget, with the feasibility verdict callers
                        gate on.
- ``train_step.py``   — ``PipelineTrainStep``: the 1F1B schedule as the
                        loss+grad engine inside ONE compiled TrainStep
                        program, composed with the quantized ``grad_comm``
                        codecs over the data axis and (optionally) stage
                        parameters held ZeRO-3-style at rest.

Importing the historical names (``from paddle_tpu.distributed.pipeline
import pipeline_1f1b``) keeps working — the package re-exports the module
surface it replaced.
"""
from .schedule import pipeline_1f1b, pipeline_spmd  # noqa: F401
from .memory_plan import (  # noqa: F401
    MemoryPlan, host_offload_supported, plan_memory,
    gpt_activation_estimate,
)
from .train_step import PipelineTrainStep  # noqa: F401

__all__ = [
    "pipeline_spmd", "pipeline_1f1b",
    "MemoryPlan", "plan_memory", "host_offload_supported",
    "gpt_activation_estimate", "PipelineTrainStep",
]

"""Overlapped gradient communication: bucket-ready async all-reduce.

PR 1's `grad_comm.GradCommunicator.sync` runs as one serial phase after
backward finishes — on the step breakdown (observability.StepTimer) the comm
time is fully exposed, none hidden under backward compute. This module hides
it ("Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training", arXiv:2004.13336; EQuARX, arXiv:2506.17615: quantized all-reduce
composes with async collectives):

- **Eager path** (`OverlappedGradCommunicator`): `prepare()` installs the
  autograd grad-ready hook (`framework.autograd.set_grad_ready_hook` — the
  Reducer's MarkVarReady analog). The moment the LAST grad of a bucket is
  deposited, the bucket's collective launches on a background
  `CollectiveLane` (one worker thread, FIFO — so collectives keep a total
  order per rank) while the rest of backward keeps running on the main
  thread. Every collective still goes through `collective.py` →
  `robustness/distributed_ft.execute_collective`, so group timeouts,
  retries, backoff, and chaos injection keep working unchanged. `flush()`
  (called by `sync()` / `apply_collective_grads`) is the step barrier: it
  launches any bucket whose grads appeared after backward (e.g.
  `find_unused_parameters` zero-fills), waits the lane out, surfaces the
  first error, and records the overlap telemetry. Results are BIT-IDENTICAL
  to the serial path: the flatten → encode → collective → decode → scatter
  pipeline is `GradCommunicator`'s own, per bucket, and buckets are
  independent (int8 error-feedback residuals are per bucket).
- **In-trace path** (`sync_async` / `BucketFuture`): inside a
  shard_map/pjit trace each bucket's psum/psum_scatter is issued as its own
  op and returned as a per-bucket future instead of being consumed at one
  barrier. XLA's latency-hiding scheduler is then free to overlap bucket
  k+1's collective with whatever consumes bucket k — the fused flat-buffer
  optimizer update (optimizer/fused.py) consumes the futures one by one for
  exactly this reason. The configured wire codec applies HERE TOO (ISSUE
  8): quantize -> psum-of-int -> dequantize is part of the compiled
  program, with error-feedback residuals threaded as carried state
  (`residuals=` in, `fut.residual` out — jit.TrainStep(grad_comm=) does
  the threading for a whole train step). Eagerly the same call returns
  already-resolved futures (jax dispatch is itself async).

Telemetry: per-bucket `comm_launch:bucket{i}` marker spans are emitted on
the MAIN thread inside backward (proof of launch-before-backward-end in the
step trace) and `comm:bucket{i}` spans on the lane thread carry the actual
transfer window; flush emits a `comm` span for the exposed wait. The
`grad_comm_overlap_efficiency` gauge is hidden_comm_time/total_comm_time of
the last flush.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import autograd as _autograd
from ..observability.flight_recorder import get_flight_recorder
from ..observability.metrics import get_registry as _get_registry
from .grad_comm import GradBucket, GradCommConfig, GradCommunicator

__all__ = [
    "BucketFuture", "CollectiveLane", "GatherFuture",
    "OverlappedGradCommunicator", "communicator_for", "overlap_report",
]

_m_overlap_eff = _get_registry().gauge(
    "grad_comm_overlap_efficiency",
    help="hidden_comm_time / total_comm_time of the last overlapped sync")
_m_overlap_syncs = _get_registry().counter(
    "grad_comm_overlapped_syncs_total",
    help="gradient syncs that ran in bucket-ready overlapped mode").bind()
_m_early = _get_registry().counter(
    "grad_comm_buckets_launched_early_total",
    help="buckets whose collective launched before backward finished").bind()


def communicator_for(config: Optional[GradCommConfig] = None, group=None):
    """GradCommunicator (serial) or OverlappedGradCommunicator, per
    `config.overlap` — the one constructor call sites need."""
    config = config or GradCommConfig()
    cls = OverlappedGradCommunicator if config.overlap else GradCommunicator
    return cls(config, group=group)


class BucketFuture:
    """Handle for one in-flight (or in-trace) bucket reduction.

    Eager/overlapped: resolved by the CollectiveLane worker; `wait()` blocks.
    In-trace: holds the already-issued collective's lazy value; `wait()` is
    immediate (XLA owns the schedule).
    """

    __slots__ = ("bucket", "_value", "_error", "_done", "launch_ns",
                 "start_ns", "end_ns", "scatter", "residual")

    def __init__(self, bucket: GradBucket, value=None, resolved=False):
        self.bucket = bucket
        self._value = value
        self._error = None
        self._done = threading.Event()
        if resolved:
            self._done.set()
        self.launch_ns = None   # submit time (main thread, inside backward)
        self.start_ns = None    # lane-side work window
        self.end_ns = None
        # error-feedback residual of this bucket's encode (sync_async):
        # None for codecs without error feedback. In-trace this is the
        # carried-state output the caller must thread into the next step
        # (jit.TrainStep does); eagerly the communicator already kept it.
        self.residual = None

    def _resolve(self, value):
        self._value = value
        self._done.set()

    def _fail(self, err):
        self._error = err
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout=None):
        """Block until resolved; returns the reduced flat buffer (raises
        the lane-side error, if any)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"bucket {self.bucket.index} collective did not complete "
                f"within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._value

    result = wait

    def __repr__(self):
        state = ("error" if self._error is not None
                 else "done" if self.done() else "pending")
        return f"BucketFuture(bucket={self.bucket.index}, {state})"


class GatherFuture(BucketFuture):
    """Handle for one in-flight ZeRO-3 parameter-bucket all_gather — the
    second CollectiveLane client (distributed/sharding/stage3.py), running
    the grad lane's collective in the inverse direction: shards in, full
    flat parameter buffer out. Launch/start/end timestamps carry the
    prefetch-vs-exposed accounting exactly like a grad BucketFuture's."""

    __slots__ = ()


class CollectiveLane:
    """Background collective lane: one daemon worker draining a FIFO.

    One lane = one thread = a total order over the collectives it runs, the
    same property a dedicated comm stream gives NCCL — ranks launching
    buckets in the same (deterministic, bucket-completion) order cannot
    deadlock. The worker exits when idle and is respawned on demand, so an
    idle communicator holds no thread.
    """

    def __init__(self, name="grad-comm-lane"):
        self.name = name
        self._lock = threading.Lock()
        self._jobs = deque()
        self._thread: Optional[threading.Thread] = None

    def submit(self, fn) -> threading.Event:
        """Queue fn for FIFO execution; returns its completion event."""
        done = threading.Event()
        with self._lock:
            self._jobs.append((fn, done))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name=self.name)
                self._thread.start()
        return done

    def _run(self):
        while True:
            with self._lock:
                if not self._jobs:
                    if self._thread is threading.current_thread():
                        self._thread = None
                    return
                fn, done = self._jobs.popleft()
            try:
                fn()
            finally:
                done.set()


class OverlappedGradCommunicator(GradCommunicator):
    """GradCommunicator whose buckets launch as backward produces them.

    Protocol (what `DataParallel` does when the strategy's
    ``grad_comm_configs["overlap"]`` is on):

        comm.prepare(params, world)      # before backward: install hooks
        loss.backward()                  # buckets launch as they complete
        comm.sync(params, world)         # == flush(): barrier + write-back

    `sync()` on a prepared step is the flush barrier; on an unprepared step
    it falls back to the serial path (still correct, nothing hidden), so
    call sites need no mode branching. Overlapped mode requires each grad's
    dtype to match its parameter's (true for this framework's eager tape;
    the hook checks and fails loudly otherwise rather than silently
    re-bucketing differently from the serial path).
    """

    def __init__(self, config: Optional[GradCommConfig] = None, group=None):
        super().__init__(config, group)
        self._lane = CollectiveLane()
        self._step = None            # per-backward state; None = not prepared
        self._prev_hook = None
        self.last_timeline: List[dict] = []

    # ------------------------------------------------------------- prepare
    def prepare(self, params, world: Optional[int] = None,
                use_reduce_scatter: bool = False):
        """Arm the next backward: build the bucket plan from the (reverse
        traversal order) parameter list and install the grad-ready hook.
        No-op (returns self) when world <= 1 or there is nothing to sync."""
        self.abandon()   # a re-arm must not leak the previous step's hook
        params = [p for p in params if not p.stop_gradient]
        if world is None:
            from .env import get_world_size

            world = get_world_size()
        if world <= 1 or not params:
            return self
        # grads don't exist yet: bucket on the param dtypes, which is what
        # the eager tape's cotangents carry (checked at hook time)
        dtypes = [np.dtype(p._value.dtype) for p in params]
        buckets = self.buckets_for(params, dtypes=dtypes)
        by_param: Dict[int, GradBucket] = {}
        for b in buckets:
            for pi in b.param_indices:
                by_param[id(params[pi])] = b
        self._step = {
            "params": params,
            "world": int(world),
            "use_reduce_scatter": bool(use_reduce_scatter),
            "buckets": buckets,
            "by_param": by_param,
            "remaining": {b.index: len(b.param_indices) for b in buckets},
            "futures": {},           # bucket index -> BucketFuture
            "dtype_error": None,
        }
        self.stats = {"codec": self.config.codec, "path": "eager",
                      "n_params": len(params), "n_buckets": len(buckets),
                      "collectives": 0, "comm_bytes": 0}
        self._prev_hook = _autograd.set_grad_ready_hook(self._on_grad_ready)
        return self

    # ---------------------------------------------------------- hook + lane
    def _on_grad_ready(self, tensor):
        st = self._step
        if st is None:
            return
        b = st["by_param"].get(id(tensor))
        if b is None:
            return
        grad = tensor.grad
        if grad is not None and np.dtype(grad._value.dtype) != b.dtype:
            # re-bucketing by grad dtype here would silently diverge from
            # the serial assignment (and the int8 residual keys) — refuse
            st["dtype_error"] = (
                f"overlapped grad sync: parameter {tensor.name!r} produced "
                f"a {grad._value.dtype} grad in a {b.dtype} bucket; "
                f"overlap requires grad dtype == param dtype (disable "
                f"grad_comm_configs['overlap'] for mixed-dtype grads)")
            return
        st["remaining"][b.index] -= 1
        if st["remaining"][b.index] == 0 and st["dtype_error"] is None:
            self._launch(b, st)

    def _launch(self, bucket: GradBucket, st):
        """Submit one completed bucket to the lane. Called on the thread
        that produced the last grad (inside backward for early launches,
        inside flush for stragglers)."""
        from ..profiler import RecordEvent

        fut = BucketFuture(bucket)
        fut.launch_ns = time.perf_counter_ns()
        st["futures"][bucket.index] = fut
        # zero-width marker in the MAIN thread's span stream: nests inside
        # the enclosing "backward" span, so the step trace proves the
        # launch happened before backward completed
        marker = RecordEvent(f"comm_launch:bucket{bucket.index}")
        marker.begin()
        marker.end()
        params, world = st["params"], st["world"]
        use_rs = st["use_reduce_scatter"]
        # flight-recorder lane entry (ISSUE 6): a hang postmortem must name
        # the bucket/group that launched and never completed
        flightrec = get_flight_recorder()
        group = repr(self.group) if self.group is not None else "world"
        flightrec.lane(f"comm_launch:bucket{bucket.index}",
                       bucket=bucket.index, group=group, phase="launch")

        def job():
            fut.start_ns = time.perf_counter_ns()
            flightrec.lane(f"comm:bucket{bucket.index}", bucket=bucket.index,
                           group=group, phase="start")
            try:
                with RecordEvent(f"comm:bucket{bucket.index}"):
                    flat = self._flatten_bucket(bucket, params)
                    reduced = self._sync_bucket(bucket, flat, world, use_rs)
                    self._scatter_bucket(bucket, params, reduced)
                    # realize the transfer inside the span so the recorded
                    # window is the work, not the async dispatch
                    v = params[bucket.param_indices[0]].grad._value
                    if hasattr(v, "block_until_ready"):
                        v.block_until_ready()
            except BaseException as e:  # surfaced by flush()
                fut._fail(e)
                flightrec.lane(f"comm:bucket{bucket.index}",
                               bucket=bucket.index, group=group,
                               phase="error", error=repr(e))
            else:
                fut._resolve(reduced)
                flightrec.lane(f"comm:bucket{bucket.index}",
                               bucket=bucket.index, group=group, phase="end")
            fut.end_ns = time.perf_counter_ns()

        self._lane.submit(job)

    def abandon(self):
        """Disarm without syncing: restore the hook and discard the step
        state (draining anything already launched). Needed before a
        backward whose grads must ACCUMULATE raw — e.g. the non-update
        micro-batches of gradient accumulation, where an early bucket
        launch would average partial grads the serial path never would."""
        st, self._step = self._step, None
        if st is None:
            return
        _autograd.set_grad_ready_hook(self._prev_hook)
        self._prev_hook = None
        for fut in st["futures"].values():
            fut._done.wait()

    # ----------------------------------------------------------------- sync
    def sync(self, params, world: Optional[int] = None,
             use_reduce_scatter: bool = False):
        """Prepared step → flush barrier; unprepared → serial fallback."""
        if self._step is None:
            return super().sync(params, world,
                                use_reduce_scatter=use_reduce_scatter)
        return self.flush()

    def flush(self):
        """Step barrier: launch stragglers, drain the lane, write back (the
        lane already scattered each bucket), account, and uninstall the
        hook. Raises the first lane-side error after the lane is drained."""
        from ..profiler import RecordEvent

        st, self._step = self._step, None
        _autograd.set_grad_ready_hook(self._prev_hook)
        self._prev_hook = None
        if st is None:
            return
        if st["dtype_error"]:
            # drain in-flight buckets before raising so no lane job is
            # left mutating grads behind the caller's back
            for fut in st["futures"].values():
                fut._done.wait()
            raise RuntimeError(st["dtype_error"])
        flush_t0 = time.perf_counter_ns()
        with RecordEvent("comm"):     # the EXPOSED comm window of this step
            # stragglers: buckets whose grads appeared outside backward
            # (zero-filled unused params, manual .grad writes) — or a
            # backward that never ran; launch them now, in bucket order
            for b in st["buckets"]:
                if b.index in st["futures"]:
                    continue
                if any(st["params"][pi].grad is None
                       for pi in b.param_indices):
                    raise RuntimeError(
                        f"overlapped grad sync: bucket {b.index} still has "
                        f"parameters with no gradient at flush time — "
                        f"DataParallel(find_unused_parameters=True) "
                        f"zero-fills them before the sync")
                self._launch(b, st)
            error = None
            for b in st["buckets"]:
                fut = st["futures"][b.index]
                fut._done.wait()
                if fut._error is not None and error is None:
                    error = fut._error
        if error is not None:
            raise error
        self._account(st, flush_t0)

    def _account(self, st, flush_t0):
        """Overlap telemetry for one flushed step: how much of the comm
        time ran under backward (before flush began) vs exposed after it."""
        timeline, total, hidden = [], 0.0, 0.0
        for b in st["buckets"]:
            fut = st["futures"][b.index]
            dur = max(0, (fut.end_ns or 0) - (fut.start_ns or 0))
            hid = max(0, min(fut.end_ns or 0, flush_t0)
                      - min(fut.start_ns or 0, flush_t0))
            total += dur
            hidden += hid
            timeline.append({
                "bucket": b.index,
                "launched_early": fut.launch_ns < flush_t0,
                "launch_ns": fut.launch_ns,
                "start_ns": fut.start_ns,
                "end_ns": fut.end_ns,
                "comm_s": dur / 1e9,
                "hidden_s": hid / 1e9,
            })
        self.last_timeline = timeline
        eff = hidden / total if total else 0.0
        early = sum(1 for row in timeline if row["launched_early"])
        self.stats.update({
            "overlapped": True,
            "hidden_comm_s": hidden / 1e9,
            "exposed_comm_s": (total - hidden) / 1e9,
            "overlap_efficiency": eff,
            "buckets_launched_early": early,
        })
        _m_overlap_syncs.value += 1
        _m_early.value += early
        _m_overlap_eff.set(round(eff, 6))
        self._record_metrics(st["buckets"])

    # ------------------------------------------------------------- in-trace
    def sync_async(self, params, world: Optional[int] = None,
                   use_reduce_scatter: bool = False,
                   residuals=None) -> List[BucketFuture]:
        """Issue every bucket's collective NOW and return per-bucket
        futures instead of blocking on one barrier.

        Inside a shard_map/pjit trace each bucket becomes its own
        psum/psum_scatter op whose result is consumed only when the
        caller's code touches that future — XLA's latency-hiding scheduler
        interleaves the collectives with compute between consumptions (the
        fused optimizer update consumes them bucket by bucket). Eagerly the
        futures resolve immediately. Write-back to `.grad` views happens
        per future via `scatter()`; callers that consume the flat buffer
        directly (optimizer/fused.py) skip the unflatten entirely.

        The configured codec is honored on BOTH paths — in-trace the
        quantize -> psum-of-int -> dequantize sequence is part of the
        compiled program, so XLA overlaps the (4x smaller) transfers.
        Error feedback in-trace is CARRIED STATE: pass the previous step's
        residuals as `residuals` ({bucket_index: fp32 flat}) and read each
        future's `.residual` back out (a tracer must never land in
        `self._residuals`); eagerly, omitting `residuals` keeps the
        communicator managing them host-side exactly as `sync()` does.
        """
        params = [p for p in params if p.grad is not None]
        if world is None:
            from .env import get_world_size

            world = get_world_size()
        self.stats = {"codec": self.config.codec, "path": "eager",
                      "n_params": len(params), "n_buckets": 0,
                      "collectives": 0, "comm_bytes": 0}
        if world <= 1 or not params:
            return []
        from .grad_comm import EF_CODECS

        dtypes = [np.dtype(p.grad._value.dtype) for p in params]
        buckets = self.buckets_for(params, dtypes=dtypes)
        self.stats["n_buckets"] = len(buckets)
        ef = self.config.error_feedback and self.config.codec in EF_CODECS
        futures = []
        path = "eager"
        for b in buckets:
            flat = self._flatten_bucket(b, params)
            if isinstance(flat, jax.core.Tracer):
                path = "traced"
            res_in = None
            if ef:
                res_in = (residuals.get(b.index) if residuals is not None
                          else self._residuals.get(b.index))
            reduced, new_res, wire_bytes, n_coll = self.reduce_bucket(
                b, flat, world, use_reduce_scatter=use_reduce_scatter,
                residual=res_in)
            if new_res is not None and residuals is None:
                if isinstance(new_res, jax.core.Tracer):
                    raise RuntimeError(
                        f"grad_comm codec {self.config.codec!r} with error "
                        f"feedback inside a trace needs the residuals "
                        f"threaded as carried state: call "
                        f"sync_async(residuals=...) and feed each "
                        f"future's .residual back next step (or use "
                        f"jit.TrainStep(grad_comm=...))")
                self._residuals[b.index] = new_res
            self.stats["collectives"] += n_coll
            self.stats["comm_bytes"] += wire_bytes
            fut = BucketFuture(b, value=reduced, resolved=True)
            fut.residual = new_res
            # bind write-back so callers can scatter lazily, per bucket
            fut.scatter = (lambda bb=b, rr=reduced:
                           self._scatter_bucket(bb, params, rr))
            futures.append(fut)
        self.stats["path"] = path
        self._record_metrics(buckets, path=path)
        return futures


# ---------------------------------------------------------------------------
# measurement helper (tools/overlap_bench.py + bench.py's gpt JSON)
# ---------------------------------------------------------------------------

def _fake_params(shapes_dtypes, seed=0):
    from ..framework.tensor import Tensor

    rs = np.random.RandomState(seed)
    params = []
    for i, (shape, dt) in enumerate(shapes_dtypes):
        p = Tensor(np.zeros(shape, dt))
        p.stop_gradient = False
        p.name = f"p{i}"
        p.grad = Tensor(rs.standard_normal(shape).astype(dt) * 1e-2)
        params.append(p)
    return params


def overlap_report(params, config: Optional[GradCommConfig] = None,
                   world: int = 2, compute_s: float = 0.02,
                   seed: int = 0) -> dict:
    """Serial vs overlapped exposed-comm measurement for one model's
    gradient sync (host emulation — the same caveat as
    tools/grad_comm_bench.py: wall times are host encode/concat costs, not
    ICI transfer). `params` provides shapes/dtypes only; grads are
    synthesized on detached fakes, so live models are never mutated.
    `compute_s` is the emulated backward duration the overlapped launches
    get to hide under, spread across the per-bucket ready events."""
    config = config or GradCommConfig()
    shapes_dtypes = [(tuple(p._value.shape), np.dtype(p._value.dtype))
                     for p in params if not p.stop_gradient]

    # ---- serial: the whole sync is exposed
    fakes = _fake_params(shapes_dtypes, seed=seed)
    serial = GradCommunicator(GradCommConfig(
        config.codec, config.comm_buffer_size, config.last_comm_buffer_size,
        config.error_feedback))
    serial.sync(fakes, world=world)        # warm caches/compiles
    fakes = _fake_params(shapes_dtypes, seed=seed)
    t0 = time.perf_counter()
    serial.sync(fakes, world=world)
    serial_exposed_s = time.perf_counter() - t0

    # ---- overlapped: emulate backward producing grads in reverse order
    fakes = _fake_params(shapes_dtypes, seed=seed)
    comm = OverlappedGradCommunicator(GradCommConfig(
        config.codec, config.comm_buffer_size, config.last_comm_buffer_size,
        config.error_feedback, overlap=True))
    comm.prepare(fakes, world=world)
    per_param = compute_s / max(1, len(fakes))
    for p in reversed(fakes):              # backward produces grads in
        time.sleep(per_param)              # reverse traversal order
        comm._on_grad_ready(p)
    t0 = time.perf_counter()
    comm.flush()
    flush_wait_s = time.perf_counter() - t0
    return {
        "codec": config.codec,
        "world": int(world),
        "n_buckets": comm.stats["n_buckets"],
        "serial_exposed_comm_ms": round(serial_exposed_s * 1e3, 3),
        "overlapped_exposed_comm_ms": round(
            comm.stats["exposed_comm_s"] * 1e3, 3),
        "overlapped_flush_wait_ms": round(flush_wait_s * 1e3, 3),
        "hidden_comm_ms": round(comm.stats["hidden_comm_s"] * 1e3, 3),
        "overlap_efficiency": round(comm.stats["overlap_efficiency"], 4),
        "buckets_launched_early": comm.stats["buckets_launched_early"],
        "emulated_backward_ms": round(compute_s * 1e3, 3),
    }

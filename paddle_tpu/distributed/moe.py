"""Mixture-of-Experts — expert parallelism.

Reference: the EP building blocks global_scatter/global_gather
(operators/collective/global_scatter_op.cc, python/paddle/distributed/
utils.py:57,179) route variable token counts between n_expert*world_size
experts with NCCL alltoall; no gating library exists in the snapshot
(SURVEY.md §2.3: "building block only").

TPU-native inversion: variable-count alltoall is hostile to XLA's static
shapes, so routing uses the GShard/Switch fixed-capacity design — top-k gating
+ one-hot dispatch einsums; expert weights carry a PartitionSpec over the
'expert' mesh axis and GSPMD emits the AllToAll from the dispatch einsum's
contraction. The reference's global_scatter/global_gather API survives in
distributed/utils.py as eager permutation semantics for compatibility.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..framework.autograd import call_op
from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from . import mesh as mesh_mod

EXPERT_AXIS = mesh_mod.AXIS_EXPERT


def _top2_gating(logits, capacity):
    """GShard top-2 gating: returns (combine [T,E,C], dispatch [T,E,C], aux)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32)
    probs2 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)

    # load-balance aux loss (Switch/GShard): E * mean(frac_tokens * frac_probs)
    density = jnp.mean(mask1, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * E

    # positions within each expert's capacity buffer
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - 1.0
    used1 = jnp.sum(mask1, axis=0, keepdims=True)
    pos2 = (jnp.cumsum(mask2, axis=0) * mask2 - 1.0) + used1 * mask2
    mask1 = mask1 * (pos1 < capacity)
    mask2 = mask2 * (pos2 < capacity)

    g1 = jnp.sum(probs * mask1, axis=-1)
    g2 = jnp.sum(probs * mask2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    loc1 = jax.nn.one_hot(jnp.sum(pos1 * mask1, axis=-1).astype(jnp.int32),
                          capacity, dtype=jnp.float32)
    loc2 = jax.nn.one_hot(jnp.sum(pos2 * mask2, axis=-1).astype(jnp.int32),
                          capacity, dtype=jnp.float32)
    combine = (g1[:, None, None] * mask1[:, :, None] * loc1[:, None, :]
               + g2[:, None, None] * mask2[:, :, None] * loc2[:, None, :])
    dispatch = combine > 0.0
    return combine, dispatch, aux


class MoELayer(Layer):
    """Gated MoE FFN: top-2 routing over `num_experts` expert MLPs, experts
    sharded over the 'expert' mesh axis (build the mesh with
    {"expert": k, ...}). Input/output [batch, seq, hidden]. The load-balance
    aux loss is stored on ``self.aux_loss`` after each forward (add
    ``aux_weight * layer.aux_loss`` to the training loss)."""

    def __init__(self, hidden_size, ffn_hidden_size, num_experts,
                 capacity_factor=1.25, init_std=0.02, seed=0, dtype="float32"):
        super().__init__()
        from ..framework import dtype as dtype_mod
        from ..framework.tensor import Parameter

        self.num_experts = int(num_experts)
        self.capacity_factor = float(capacity_factor)
        rs = np.random.RandomState(seed)
        dt = dtype_mod.convert_dtype(dtype)

        def param(shape, std, spec):
            p = Parameter(Tensor((rs.randn(*shape) * std).astype("float32"),
                                 dtype=dt)._value, trainable=True)
            p.dist_spec = spec
            p.is_distributed = True
            return p

        E, H, F_ = self.num_experts, hidden_size, ffn_hidden_size
        self.gate_w = param([H, E], init_std, None)
        self.w_in = param([E, H, F_], init_std, P(EXPERT_AXIS, None, "model"))
        self.b_in = param([E, F_], 0.0, P(EXPERT_AXIS, "model"))
        self.w_out = param([E, F_, H], init_std, P(EXPERT_AXIS, "model", None))
        self.b_out = param([E, H], 0.0, P(EXPERT_AXIS, None))
        self.aux_loss = None

    def forward(self, x):
        E = self.num_experts
        cf = self.capacity_factor

        def fn(xv, gw, wi, bi, wo, bo):
            b, s, h = xv.shape
            T = b * s
            cap = max(1, int(math.ceil(T * cf / E)))
            tokens = xv.reshape(T, h)
            logits = tokens.astype(jnp.float32) @ gw.astype(jnp.float32)
            combine, dispatch, aux = _top2_gating(logits, cap)
            combine = combine.astype(xv.dtype)
            # dispatch: [T,E,C] x [T,H] -> [E,C,H]; GSPMD AllToAlls to experts
            ein = jnp.einsum("tec,th->ech", dispatch.astype(xv.dtype), tokens)
            ein = _constrain(ein, EXPERT_AXIS, None, None)
            z = jnp.einsum("ech,ehf->ecf", ein, wi) + bi[:, None, :]
            z = jax.nn.gelu(z, approximate=True)
            z = jnp.einsum("ecf,efh->ech", z, wo) + bo[:, None, :]
            z = _constrain(z, EXPERT_AXIS, None, None)
            out = jnp.einsum("tec,ech->th", combine, z)
            return out.reshape(b, s, h), aux

        out, aux = call_op(fn, x, self.gate_w, self.w_in, self.b_in,
                           self.w_out, self.b_out, op_name="moe_layer")
        self.aux_loss = aux
        return out


def _constrain(v, *spec):
    m = mesh_mod.get_mesh()
    if m is None:
        return v
    from jax.sharding import NamedSharding

    return jax.lax.with_sharding_constraint(
        v, NamedSharding(m, mesh_mod.sanitize_spec(P(*spec), m)))

"""Ulysses (DeepSpeed-style) sequence parallelism over the 'sep' mesh axis.

NET-NEW vs the reference (SURVEY.md §5: shjNT/Paddle has no SP/CP at all).
Complements ring attention (ring_attention.py) as the second canonical SP
scheme (SURVEY §7 step 5: "ring attention ... + Ulysses-style head/sequence
all_to_all"):

- activations stay sequence-sharded over 'sep' everywhere EXCEPT inside
  attention;
- at the attention boundary one all_to_all per q/k/v swaps the sharded dim:
  [b, s/P, n, d] -> [b, s, n/P, d] (full sequence, 1/P of the heads), the
  softmax runs exactly as on one device (no online-merge needed), and one
  all_to_all swaps back;
- total comm is 4 all_to_alls of the activation size, independent of
  sequence length — cheaper than the ring's (P-1) k/v rotations when heads
  are plentiful; the ring wins when n < P or when overlap hides the ring
  hops. Both are exposed; models pick per config.

Head count must be divisible by the 'sep' degree (times the 'model' degree
when TP is also active) — the same constraint DeepSpeed-Ulysses documents.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod
from .ring_attention import _axes_in, _plain_attention


def ulysses_attention_manual(ql, kl, vl, axis: str, causal: bool = True,
                             use_flash: bool = True):
    """Body for code already inside a shard_map manual region over `axis`.
    ql/kl/vl: local [b, s_loc, n_loc, d]. The head axis must be divisible
    by the axis size."""
    # jax.lax.axis_size is newer-jax only; psum of 1 over the axis is the
    # portable spelling and is static under shard_map
    sp = int(jax.lax.psum(1, axis))
    n_loc = ql.shape[2]
    if n_loc % sp != 0:
        raise ValueError(
            f"ulysses: local head count {n_loc} not divisible by "
            f"sep degree {sp}")
    # seq-sharded -> head-sharded: [b, s/P, n, d] -> [b, s, n/P, d]
    swap_in = lambda t: jax.lax.all_to_all(  # noqa: E731
        t, axis, split_axis=2, concat_axis=1, tiled=True)
    swap_out = lambda t: jax.lax.all_to_all(  # noqa: E731
        t, axis, split_axis=1, concat_axis=2, tiled=True)
    q = swap_in(ql)
    k = swap_in(kl)
    v = swap_in(vl)

    from ..framework.target import target_platform

    if use_flash and target_platform() == "tpu":
        from ..ops.flash_attention import (
            flash_attention_supported, flash_attention_val,
        )

        if causal and flash_attention_supported(tuple(q.shape), block=256):
            return swap_out(flash_attention_val(q, k, v, causal=True,
                                                block_size=256))
    return swap_out(_plain_attention(q, k, v, causal))


def ulysses_attention_val(q, k, v, axis: str = "sep", causal: bool = True,
                          use_flash: bool = True):
    """Value-level Ulysses attention. q/k/v: [batch, seq, heads, head_dim]
    with seq sharded over `axis`. Returns the same shape/sharding.
    Traceable under jit; enters a shard_map manual region."""
    mesh = mesh_mod.get_mesh()
    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return _plain_attention(q, k, v, causal)

    batch_ax = _axes_in(mesh, ("data", "sharding"))
    head_ax = _axes_in(mesh, ("model",))
    spec = P(batch_ax, axis, head_ax, None)

    @partial(mesh_mod.compat_shard_map, mesh=mesh,
             in_specs=(spec, spec, spec), out_specs=spec)
    def swap(ql, kl, vl):
        return ulysses_attention_manual(ql, kl, vl, axis, causal=causal,
                                        use_flash=use_flash)

    return swap(q, k, v)


def ulysses_attention(q, k, v, causal: bool = True, axis: str = "sep"):
    """Tensor-level API: paddle_tpu.distributed.ulysses_attention."""
    from ..framework.autograd import call_op

    return call_op(
        lambda a, b, c: ulysses_attention_val(a, b, c, axis=axis,
                                              causal=causal),
        q, k, v, op_name="ulysses_attention")

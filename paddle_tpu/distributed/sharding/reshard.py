"""Elastic resharding: transform a sharded checkpoint from world=N to M.

The PR-1/9 bucket layout (the weight-update-sharding layout of Xu et al.,
arXiv:2004.13336) makes every per-rank artifact — ZeRO-3 at-rest parameter
shards, `FusedFlatUpdater` shard slot buffers, reduce_scatter grad shards —
the same ``[rank*chunk, (rank+1)*chunk)`` slice of one flat per-bucket
buffer, where ``chunk = ceil(size / world)`` and the buffer is zero-padded
to ``world * chunk``. The shard geometry is therefore a pure function of
(bucket sizes, world): an N→M transform is mechanical —

    1. reconstruct each flat bucket HOST-side by concatenating the N rank
       shards and stripping the N-padding back to the true bucket size;
    2. re-pad to ``M * ceil(size / M)`` and slice M new rank shards.

For fp32 payloads (parameters, optimizer slot buffers) this is bit-exact:
the transform is a relabeling of the same bytes, so the result is
BIT-IDENTICAL to the gather→rewrap reference (materialize the full
parameters at N, shard them fresh at M) — tests/test_reshard.py pins it.

Error-feedback residuals (the int8/fp8 codecs' cross-step quantization
error) are NOT sharded — each rank carries a full-bucket-sized local
residual. Resharding policy: **sum per element across the old ranks, then
re-split 1/M to every new rank** (``new_r = Σ_old res / M``). What matters
for convergence is the TOTAL error mass re-injected at the next sync
(each rank adds its residual to its local gradient before encoding and
the encoded payloads are summed over ranks), and the policy preserves
that sum exactly: Σ_new new_r = Σ_old res. In single-process emulation the
world shares one communicator, so the single residual map passes through
unchanged (N_maps = M_maps = 1) and resumed training is bit-identical.

Entry points:

- :func:`reshard_payloads` — pure host transform over the per-rank
  payload dicts `save_group_sharded_checkpoint` writes.
- :func:`reshard_checkpoint` — load a sharded checkpoint at ``step`` from
  a :class:`~paddle_tpu.robustness.checkpoint.CheckpointManager`,
  transform, and commit the world-M checkpoint back at the same step
  (manifest-gated; the old-geometry checkpoint is replaced atomically).
  Counted on ``reshard_total{from_world,to_world}`` and timed into the
  ``reshard_ms`` gauge (gated by tools/bench_gate.py).
- `CheckpointManager.load_sharded(..., allow_reshard=True)` and
  `ElasticController`'s scale-restart path call in here so a drifted
  geometry triggers the transform instead of refusing the resume.

Both the emulated single-process layout (one shard file whose zero3 state
carries ``peer_shards``) and the real multi-file layout (one payload per
rank, own shards only) are supported; the output keeps the input's style.
"""
from __future__ import annotations

import copy
import time
from typing import Dict, List, Optional

import numpy as np

from ...framework.errors import CheckpointCorruptError
from ...observability import get_event_log
from ...observability.metrics import get_registry as _get_registry

__all__ = [
    "chunk_of", "rechunk_flat", "assemble_full_buckets",
    "reshard_zero3_states", "reshard_slot_states", "reshard_residual_maps",
    "reshard_payloads", "reshard_checkpoint", "reshard_report",
]

# elastic-resharding telemetry: how often geometry-drifted resumes were
# transformed instead of refused, and what the transform costs — the
# numbers that decide whether preemption-tolerant shrink is cheap enough
# to run on every rank loss
_m_reshards = _get_registry().counter(
    "reshard_total",
    help="sharded checkpoints resharded to a new world size",
    labels=("from_world", "to_world"))
_m_reshard_ms = _get_registry().gauge(
    "reshard_ms", help="wall ms of the last N->M checkpoint reshard")


def chunk_of(size: int, world: int) -> int:
    """Per-rank chunk of a flat bucket: ceil(size / world) — the PR-1/9
    padding geometry every sharded artifact in this repo uses."""
    size, world = int(size), int(world)
    return (size + (-size) % world) // world


def rechunk_flat(full: np.ndarray, size: int, world: int) -> List[np.ndarray]:
    """Slice an unpadded flat buffer into `world` padded rank chunks."""
    full = np.asarray(full).reshape(-1)[:size]
    c = chunk_of(size, world)
    pad = c * world - size
    if pad:
        full = np.concatenate([full, np.zeros((pad,), full.dtype)])
    return [full[r * c:(r + 1) * c] for r in range(world)]


def _bucket_sizes_of(state: dict, what: str) -> Dict[int, int]:
    sizes = state.get("bucket_sizes")
    if not sizes:
        raise CheckpointCorruptError(
            f"{what} predates elastic resharding: it carries no "
            f"'bucket_sizes', so the N-padding cannot be stripped before "
            f"re-chunking — re-save the checkpoint with this version "
            f"before changing the world size")
    return {int(i): int(n) for i, n in sizes.items()}


def _is_emulated_zero3(states: List[dict]) -> bool:
    return len(states) == 1 and bool(states[0].get("peer_shards"))


def assemble_full_buckets(states: List[dict]) -> Dict[int, np.ndarray]:
    """Reconstruct every flat bucket (unpadded) from zero3 shard states —
    either one emulated state (own + peer shards) or one state per rank."""
    sizes = _bucket_sizes_of(states[0], "zero3 shard state")
    old_world = int(states[0]["world"])
    full = {}
    if _is_emulated_zero3(states):
        st = states[0]
        own_rank = int(st["rank"])
        for i, size in sizes.items():
            parts = []
            for r in range(old_world):
                if r == own_rank:
                    parts.append(np.asarray(st["shards"][i]))
                else:
                    parts.append(np.asarray(st["peer_shards"][i][r]))
            full[i] = np.concatenate(parts)[:size]
    else:
        if len(states) != old_world:
            raise CheckpointCorruptError(
                f"zero3 reshard needs every rank's shard state: world is "
                f"{old_world} but {len(states)} states were given")
        by_rank = {int(s["rank"]): s for s in states}
        for i, size in sizes.items():
            parts = [np.asarray(by_rank[r]["shards"][i])
                     for r in range(old_world)]
            full[i] = np.concatenate(parts)[:size]
    return full


def reshard_zero3_states(states: List[dict], new_world: int) -> List[dict]:
    """N→M transform of `Stage3ParamShards.state_dict()` snapshots.

    Input/output style match: one emulated state in (own + peer shards) →
    one emulated state out at world M; N real per-rank states in → M out.
    fp32-bit-exact: the flat bucket bytes are only re-sliced.
    """
    new_world = int(new_world)
    sizes = _bucket_sizes_of(states[0], "zero3 shard state")
    full = assemble_full_buckets(states)
    key = states[0].get("bucket_key")
    emulated = _is_emulated_zero3(states)

    chunks = {i: rechunk_flat(full[i], sizes[i], new_world) for i in full}
    if emulated:
        out = {
            "bucket_key": key, "rank": 0, "world": new_world,
            "bucket_sizes": dict(sizes),
            "shards": {i: chunks[i][0] for i in chunks},
            "peer_shards": {i: {r: chunks[i][r]
                                for r in range(1, new_world)}
                            for i in chunks},
        }
        return [out]
    return [{
        "bucket_key": key, "rank": r, "world": new_world,
        "bucket_sizes": dict(sizes),
        "shards": {i: chunks[i][r] for i in chunks},
    } for r in range(new_world)]


def _is_scalar_slot(v) -> bool:
    return np.shape(v) == ()


def reshard_slot_states(slot_states: List[dict], new_world: int,
                        old_world: Optional[int] = None) -> List[dict]:
    """N→M transform of `FusedFlatUpdater.shard_slots_state()` snapshots.

    Slot buffers (Adam moments etc.) are laid out exactly like the
    parameter shards, so the transform is the same strip-and-re-chunk;
    scalar slots (shared beta pows) are identical on every rank and are
    copied through. Emulated input (rank 0's ``own`` + ``peer`` entries)
    yields emulated output; N per-rank states yield M.
    """
    new_world = int(new_world)
    sizes = _bucket_sizes_of(slot_states[0], "fused shard-slot state")
    emulated = len(slot_states) == 1 and bool(slot_states[0].get("peer"))
    if old_world is None:
        if emulated:
            old_world = 1 + max((r for (_i, r) in slot_states[0]["peer"]),
                                default=0)
        else:
            old_world = len(slot_states)

    def slots_of(rank: int, bucket: int) -> Optional[dict]:
        if emulated:
            st = slot_states[0]
            if rank == 0:
                return (st.get("own") or {}).get(bucket)
            return (st.get("peer") or {}).get((bucket, rank))
        return (slot_states[rank].get("own") or {}).get(bucket)

    buckets = sorted(sizes)
    # join: full flat buffer per (bucket, slot key); scalars from rank 0
    joined: Dict[int, Dict[str, object]] = {}
    for i in buckets:
        ref = slots_of(0, i)
        if ref is None:
            continue  # bucket never stepped — no slots to transform
        out = {}
        for k, v in ref.items():
            if _is_scalar_slot(v):
                out[k] = v
            else:
                parts = []
                for r in range(old_world):
                    s = slots_of(r, i)
                    if s is None:
                        raise CheckpointCorruptError(
                            f"fused shard slots for bucket {i} missing on "
                            f"rank {r} — every rank of a stepped bucket "
                            f"must carry its slot shard")
                    parts.append(np.asarray(s[k]))
                out[k] = np.concatenate(parts)[:sizes[i]]
        joined[i] = out

    def chunked(i: int, r: int) -> dict:
        out = {}
        for k, v in joined[i].items():
            if _is_scalar_slot(v):
                out[k] = v
            else:
                out[k] = rechunk_flat(v, sizes[i], new_world)[r]
        return out

    if emulated:
        return [{
            "own": {i: chunked(i, 0) for i in joined},
            "peer": {(i, r): chunked(i, r)
                     for i in joined for r in range(1, new_world)},
            "bucket_sizes": dict(sizes),
        }]
    return [{
        "own": {i: chunked(i, r) for i in joined},
        "peer": {},
        "bucket_sizes": dict(sizes),
    } for r in range(new_world)]


def reshard_residual_maps(maps: List[dict], new_count: int) -> List[dict]:
    """Error-feedback residual policy: sum per element across the old
    ranks, then re-split 1/M to every new rank — preserves the total
    error mass the next sync re-injects (Σ_new = Σ_old). A single shared
    map (single-process emulation: one communicator for the whole world)
    passes through unchanged."""
    new_count = int(new_count)
    maps = [m or {} for m in maps]
    if len(maps) == 1 and new_count == 1:
        return [dict(maps[0])]
    keys = sorted({int(k) for m in maps for k in m})
    summed = {}
    for k in keys:
        parts = [np.asarray(m[k], dtype=np.float32) for m in maps if k in m]
        summed[k] = np.sum(parts, axis=0)
    return [{k: summed[k] / new_count for k in keys}
            for _ in range(new_count)]


def _reshard_job_state(js: dict, rank: int, new_world: int,
                       residuals: Optional[dict]) -> dict:
    js = copy.deepcopy(js)
    js["rank"] = int(rank)
    if "zero3" in js and isinstance(js["zero3"], dict):
        js["zero3"] = dict(js["zero3"], world=int(new_world), rank=int(rank))
    if residuals is not None and "grad_comm" in js:
        js["grad_comm"] = dict(js["grad_comm"], residuals=residuals)
    return js


def reshard_payloads(payloads: List[dict], new_world: int) -> List[dict]:
    """Transform the per-rank payload dicts of one sharded checkpoint
    (`save_group_sharded_checkpoint`'s layout: optional ``zero3`` /
    ``model`` / ``optimizer`` / ``fused_shard_slots`` / ``job_state``
    entries) from their current sharding world to ``new_world``.

    Emulated checkpoints (one payload whose zero3 state carries peer
    shards) come back as one payload; real N-payload checkpoints come
    back as ``new_world`` payloads. Replicated entries (``model``,
    ``optimizer``) are taken from rank 0; rank-local ``job_state`` is
    re-derived per new rank with the residual re-split policy applied.
    """
    new_world = int(new_world)
    if not payloads:
        raise ValueError("reshard_payloads needs at least one payload")
    z3_states = [p["zero3"] for p in payloads if "zero3" in p]
    emulated = bool(z3_states) and _is_emulated_zero3(z3_states)
    out_count = 1 if emulated else new_world

    new_z3 = (reshard_zero3_states(z3_states, new_world)
              if z3_states else None)
    slot_states = [p["fused_shard_slots"] for p in payloads
                   if "fused_shard_slots" in p]
    new_slots = (reshard_slot_states(slot_states, new_world)
                 if slot_states else None)

    job_states = [p.get("job_state") for p in payloads]
    have_js = [js for js in job_states if js is not None]
    new_res = None
    if have_js and not emulated:
        res_maps = [(js.get("grad_comm") or {}).get("residuals") or {}
                    for js in have_js]
        if any(res_maps):
            new_res = reshard_residual_maps(res_maps, out_count)

    out = []
    for r in range(out_count):
        p = {}
        if new_z3 is not None:
            p["zero3"] = new_z3[r]
        elif "model" in payloads[0]:
            p["model"] = copy.deepcopy(payloads[0]["model"])
        if "optimizer" in payloads[0]:
            p["optimizer"] = copy.deepcopy(payloads[0]["optimizer"])
        if new_slots is not None:
            p["fused_shard_slots"] = new_slots[r]
        if have_js:
            base = job_states[r] if r < len(job_states) and \
                job_states[r] is not None else have_js[0]
            p["job_state"] = _reshard_job_state(
                base, r, new_world,
                new_res[r] if new_res is not None else None)
        out.append(p)
    return out


def _sharding_world_of(payloads: List[dict], file_world: int) -> int:
    """The checkpoint's SHARDING world: the zero3 store's world when one
    is present (covers the emulated one-file layout), else the shard-file
    count."""
    for p in payloads:
        z3 = p.get("zero3")
        if isinstance(z3, dict) and "world" in z3:
            return int(z3["world"])
    return int(file_world)


def reshard_checkpoint(manager, step: int, new_world: int, metadata=None):
    """Load the sharded checkpoint at `step` from `manager`, transform it
    to ``new_world``, and commit the result back AT THE SAME STEP (the
    atomic manifest-gated commit replaces the old-geometry directory, so
    `load_latest` / `load_sharded` immediately see the new geometry).

    No-op (returns the manifest unchanged) when the geometry already
    matches. Raises CheckpointCorruptError when the step is missing,
    invalid, or not sharded. Returns the new manifest.
    """
    new_world = int(new_world)
    manifest = manager.validate(step)
    if manifest is None:
        raise CheckpointCorruptError(
            f"reshard: checkpoint step {step} under {manager.root!r} is "
            f"missing or fails validation")
    if not manifest.get("sharded"):
        raise CheckpointCorruptError(
            f"reshard: checkpoint step {step} is not sharded — an "
            f"unsharded checkpoint has no geometry to transform")
    file_world = int(manifest["world_size"])
    payloads = [manager.load(step, shard=r) for r in range(file_world)]
    from_world = _sharding_world_of(payloads, file_world)
    if from_world == new_world:
        return manifest
    t0 = time.perf_counter()
    new_payloads = reshard_payloads(payloads, new_world)
    meta = dict(manifest.get("metadata") or {})
    meta.update(dict(metadata or {}))
    meta["resharded_from"] = from_world
    meta["resharded_to"] = new_world
    for r, p in enumerate(new_payloads):
        manager.save_shard(p, step, r, len(new_payloads))
    manager.finalize_sharded(step, len(new_payloads), metadata=meta)
    ms = (time.perf_counter() - t0) * 1e3
    _m_reshards.labels(from_world=str(from_world),
                       to_world=str(new_world)).inc()
    _m_reshard_ms.set(round(ms, 3))
    get_event_log().info(
        "reshard", "sharded checkpoint resharded", step=int(step),
        from_world=from_world, to_world=new_world, ms=round(ms, 3),
        shard_files=len(new_payloads))
    return manager.validate(step)


# ---------------------------------------------------------------------------
# measurement helper (bench.py + tools/bench_gate.py's reshard_ms gate)
# ---------------------------------------------------------------------------

def reshard_report(params, config=None, old_world: int = 4,
                   new_world: int = 2, seed: int = 0) -> dict:
    """Time the N→M zero3 shard transform on detached fakes of `params`'
    shapes (host cost only — the transform IS host-side by design) and
    verify bit-identity against the gather→rewrap reference in passing."""
    from ..grad_comm import GradCommConfig, GradCommunicator
    from .stage3 import Stage3ParamShards, _fake_params

    config = config or GradCommConfig()
    shapes_dtypes = [(tuple(p._value.shape), np.dtype(p._value.dtype))
                     for p in params if not p.stop_gradient]
    fakes = _fake_params(shapes_dtypes, seed=seed)
    want = [np.asarray(p._value).copy() for p in fakes]
    store = Stage3ParamShards(fakes, GradCommunicator(config), rank=0,
                              world=old_world)
    store.shard_()
    state = store.state_dict()
    t0 = time.perf_counter()
    new_states = reshard_zero3_states([state], new_world)
    ms = (time.perf_counter() - t0) * 1e3
    # gather→rewrap reference: the transformed shards must reassemble to
    # the original full parameters bit for bit
    full = assemble_full_buckets(new_states)
    ok = True
    for b in store.buckets:
        flat = full[b.index]
        for pi, o, n, shape in zip(b.param_indices, b.offsets, b.numels,
                                   b.shapes):
            ok = ok and np.array_equal(
                flat[o:o + n].reshape(shape).astype(want[pi].dtype),
                want[pi])
    _m_reshard_ms.set(round(ms, 3))
    return {
        "from_world": int(old_world), "to_world": int(new_world),
        "n_buckets": len(store.buckets),
        "param_bytes_full": int(store.stats["param_bytes_full"]),
        "reshard_ms": round(ms, 3),
        "bit_identical": bool(ok),
    }

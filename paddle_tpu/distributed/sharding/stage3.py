"""ZeRO stage-3: parameters sharded at rest, lane-prefetched all_gathers.

Reference: python/paddle/distributed/fleet/meta_parallel/sharding/
sharding_stage3.py — GroupShardedStage3 keeps every parameter as a 1/N
slice per rank and gathers the full tensor just in time for the layer that
needs it, freeing it again after use. "Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training" (PAPERS.md) is the weight-update /
memory half of that design; this module is the parameter-side completion
for the eager path (the compiled path already gets stage-3 placement from
GSPMD `dist_spec` annotations — see `group_sharded_parallel`).

Lifetime discipline (one bucket of parameters at a time)::

    shard  --prefetch-->  inflight  --wait+scatter-->  gathered
      ^                                                   |
      +------------------- free (after use) --------------+

- **At rest** every parameter's full value is FREED: its ``_value`` is a
  :class:`FreedParamValue` placeholder (shape/dtype metadata only) and the
  only device-resident copy is this rank's 1/world shard of the flat
  bucket (`GradBucket` layout shared with grad_comm, so grad reduce_scatter
  shards and optimizer-update shards all line up element for element).
- **Prefetch** is the inverse of the PR-5 grad-ready hook: a forward
  PRE-hook on layer k enqueues the all_gather for layer k+1's bucket on a
  second :class:`~paddle_tpu.distributed.overlap.CollectiveLane` client
  ("zero3-gather-lane") so the wire time hides under layer k's compute;
  the FIRST bucket has nothing to hide under and is gathered synchronously.
- **Free after use**: a forward POST-hook frees a bucket the moment its
  last using layer finished, so at most ~2 buckets of full parameters
  (current + prefetched next) are ever resident — the watermark
  `observability.memory.LiveBytesWatermark` proves in tests.
- **Backward** needs no re-gather for hook-covered parameters: the eager
  tape's vjp pullbacks captured the forward-time values as residuals (the
  re-gather of the reference design, without the wire traffic). A
  parameter read OUTSIDE its owning layer's forward (e.g. a tied embedding
  consumed by the LM head) self-heals: the placeholder's ``__array__``
  triggers an exposed synchronous gather (counted on
  ``zero3_gathers_total{mode="fallback"}``) — declare such uses with
  :meth:`Stage3ParamShards.register_external_use` to get them prefetched.
- **Update** runs on the owned shard only:
  ``FusedFlatUpdater.step_sharded(..., param_store=store)`` consumes the
  reduce_scatter grad shard and commits the new parameter shard straight
  back here — the full parameter is never materialized for the update.

Gathers ride ``distributed.collective.all_gather``, so the PR-4 timeout /
retry / chaos machinery applies on the lane. In a single-process run
(tests, CPU emulation) the eager all_gather degenerates to a clone; the
store then keeps the peer ranks' shards HOST-side (numpy) and assembles
the full buffer from them — the device-resident set is still exactly this
rank's shard, which is what `live_tensor_bytes` measures, so the memory
claim stays honest under emulation.

Telemetry: `gather_launch:bucket{i}` marker spans on the MAIN thread (the
layer-order proof that the launch precedes the bucket's first use),
`gather:bucket{i}` spans on the lane thread, `gather_sync:bucket{i}` for
exposed synchronous gathers, flight-recorder lane entries for postmortems,
and the `zero3_*` gauge/counter families below.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .. import collective as _coll
from ..grad_comm import GradCommConfig, GradCommunicator
from ..overlap import CollectiveLane, GatherFuture
from ...framework.tensor import Tensor
from ...observability import memory as obs_memory
from ...observability.flight_recorder import get_flight_recorder
from ...observability.metrics import get_registry as _get_registry

__all__ = ["FreedParamValue", "Stage3ParamShards", "zero3_gather_report"]

SHARDED, INFLIGHT, GATHERED = "sharded", "inflight", "gathered"

# one process-wide dispatch materializer covers every store: the
# placeholder itself knows its store/bucket. Installed on the first
# shard_() so processes that never shard pay only autograd's None check.
_materializer_installed = [False]


def _materialize_dispatch_value(v):
    if type(v) is FreedParamValue:
        return v.materialize()
    return v


def _install_materializer():
    if not _materializer_installed[0]:
        from ...framework import autograd as _autograd

        _autograd.set_value_materializer(_materialize_dispatch_value)
        _materializer_installed[0] = True

_m_param_bytes = _get_registry().gauge(
    "zero3_param_bytes_per_rank",
    help="device-resident parameter bytes at rest under ZeRO-3 (this "
         "rank's shards)")
_m_resident = _get_registry().gauge(
    "zero3_gathered_buckets",
    help="parameter buckets currently materialized full (gathered)")
_m_exposed = _get_registry().gauge(
    "zero3_exposed_gather_ms",
    help="exposed (not hidden under compute) parameter-gather ms of the "
         "last forward pass")
_m_gathers = _get_registry().counter(
    "zero3_gathers_total",
    help="parameter-bucket all_gathers by launch mode",
    labels=("mode",))


class FreedParamValue:
    """Placeholder standing in for a freed (sharded-at-rest) parameter.

    Carries shape/dtype metadata so planning code keeps working (bucket
    assignment keys, `Tensor.shape`, grad-hook dtype checks); reading the
    DATA triggers the store's self-healing fallback gather — or a loud
    error naming the lifecycle contract when no store is attached.
    """

    __slots__ = ("shape", "dtype", "_store", "_bucket", "_pname")

    def __init__(self, shape, dtype, store=None, bucket=None, pname=""):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self._store = store
        self._bucket = bucket
        self._pname = pname

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def nbytes(self):
        return self.size * self.dtype.itemsize

    def materialize(self):
        """Exposed synchronous re-gather of the owning bucket; returns this
        parameter's full device value. The self-healing path for reads the
        forward hooks did not cover (autograd.set_value_materializer routes
        dispatched placeholders here)."""
        if self._store is None:
            raise RuntimeError(
                f"parameter {self._pname!r} is sharded at rest (ZeRO-3) and "
                f"its full value was freed after use; gather its bucket "
                f"before reading (Stage3ParamShards.ensure_gathered)")
        return self._store._fallback_read(self._bucket, self._pname,
                                          self.shape, self.dtype)

    def __array__(self, dtype=None, copy=None):
        arr = np.asarray(self.materialize())
        return arr.astype(dtype) if dtype is not None else arr

    def __repr__(self):
        return (f"FreedParamValue(shape={self.shape}, dtype={self.dtype}, "
                f"bucket={self._bucket})")


class Stage3ParamShards:
    """At-rest parameter shards + the gather/free lifecycle for one model.

    The bucket layout is the COMMUNICATOR's own (`buckets_for` on the
    trainable parameter list), so the grad reduce_scatter shard, the
    optimizer-update shard, and the at-rest parameter shard of bucket i
    are the same ``[rank*chunk, (rank+1)*chunk)`` slice of the same flat
    buffer. ``world`` is the sharding degree (the eager process world /
    sharding-group size); ``rank`` this process's slice.
    """

    def __init__(self, params, communicator: Optional[GradCommunicator] = None,
                 rank: Optional[int] = None, world: Optional[int] = None,
                 group=None, prefetch_ahead: int = 1,
                 free_after_use: bool = True,
                 config: Optional[GradCommConfig] = None):
        from ..env import get_rank, get_world_size

        self.params = [p for p in params if not p.stop_gradient]
        self.comm = communicator or GradCommunicator(config or
                                                     GradCommConfig())
        self.rank = get_rank() if rank is None else int(rank)
        self.world = get_world_size() if world is None else int(world)
        if self.world <= 1:
            raise ValueError(
                "Stage3ParamShards needs world > 1 — with one rank there is "
                "nothing to shard (group_sharded_parallel leaves the model "
                "unsharded in that case)")
        if not (0 <= self.rank < self.world):
            raise ValueError(f"rank {self.rank} outside world {self.world}")
        self.group = group if group is not None else self.comm.group
        self.prefetch_ahead = max(0, int(prefetch_ahead))
        self.free_after_use = bool(free_after_use)
        self.buckets = self.comm.buckets_for(self.params)
        self._by_param: Dict[int, int] = {}
        for b in self.buckets:
            for pi in b.param_indices:
                self._by_param[id(self.params[pi])] = b.index
        # second CollectiveLane client (the grad lane's inverse direction)
        self._lane = CollectiveLane("zero3-gather-lane")
        self._lock = threading.Lock()     # guards _state/_futures handoff
        # single-process emulation: the eager all_gather degenerates to a
        # clone, so peer shards are kept HOST-side (numpy) — device memory
        # still holds only this rank's shard
        n_coll = _coll._group_size(_coll._axes(self.group), self.group)
        self.emulated = n_coll < self.world
        self._shards: Dict[int, object] = {}         # bucket -> jnp shard
        self._peer_shards: Dict[int, Dict[int, np.ndarray]] = {}
        self._state: Dict[int, str] = {}
        self._futures: Dict[int, GatherFuture] = {}
        self._hook_handles: List = []
        self._layer_order: List = []       # [(layer, [bucket indices])]
        self._external: Dict[int, List] = {}    # id(layer) -> [params]
        self._uses_left: Dict[int, int] = {}
        self._pass_active = False
        self.exposed_gather_s = 0.0        # since last reset_exposed()
        self._pass_exposed_s = 0.0
        self.sharded = False
        self.stats: Dict[str, object] = {
            "world": self.world, "rank": self.rank,
            "n_buckets": len(self.buckets),
            "param_bytes_full": sum(b.nbytes for b in self.buckets),
        }

    # ------------------------------------------------------------- geometry
    def _chunk(self, bucket) -> int:
        return (bucket.size + (-bucket.size) % self.world) // self.world

    def param_bytes_per_rank(self) -> int:
        """Device-resident parameter bytes at rest (this rank's shards)."""
        return sum(self._chunk(b) * b.dtype.itemsize for b in self.buckets)

    def resident_buckets(self) -> List[int]:
        return [i for i, s in self._state.items() if s == GATHERED]

    # ------------------------------------------------------------- sharding
    def shard_(self):
        """Drop to at-rest state: keep 1/world of every bucket on device,
        free the full parameter values. Idempotent."""
        if self.sharded:
            return self
        _install_materializer()
        for b in self.buckets:
            flat = self._flatten_params(b)
            chunk = self._chunk(b)
            pad = chunk * self.world - b.size
            if pad:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad,), flat.dtype)])
            # own shard is a fresh device buffer; the concatenated full
            # buffer dies with this scope
            self._shards[b.index] = flat[self.rank * chunk:
                                         (self.rank + 1) * chunk]
            if self.emulated:
                # np.array (copy): a zero-copy np.asarray view would pin
                # the device buffer and void the at-rest memory win
                self._peer_shards[b.index] = {
                    r: np.array(flat[r * chunk:(r + 1) * chunk])
                    for r in range(self.world) if r != self.rank}
            self._state[b.index] = SHARDED
            self._free_params(b)
        self.sharded = True
        _m_param_bytes.set(self.param_bytes_per_rank())
        _m_resident.set(0)
        obs_memory.sample_watermarks()
        return self

    def _flatten_params(self, bucket):
        if len(bucket.param_indices) == 1:
            return self.params[bucket.param_indices[0]]._value.reshape(-1)
        return jnp.concatenate([self.params[pi]._value.reshape(-1)
                                for pi in bucket.param_indices])

    def _free_params(self, bucket):
        for pi in bucket.param_indices:
            p = self.params[pi]
            p._value = FreedParamValue(
                p._value.shape, p._value.dtype, store=self,
                bucket=bucket.index, pname=p.name)

    # ------------------------------------------------------ gather lifecycle
    def prefetch_bucket(self, index: int):
        """Launch bucket `index`'s all_gather on the lane (the layer-ahead
        prefetch). No-op unless the bucket is at rest."""
        from ...profiler import RecordEvent

        with self._lock:
            if (not self.sharded or self._state.get(index) != SHARDED
                    or index in self._futures):
                return None
            fut = GatherFuture(self.buckets[index])
            fut.launch_ns = time.perf_counter_ns()
            self._futures[index] = fut
            self._state[index] = INFLIGHT
        # zero-width marker in the MAIN thread's span stream: the proof the
        # launch preceded the bucket's first forward use
        marker = RecordEvent(f"gather_launch:bucket{index}")
        marker.begin()
        marker.end()
        flightrec = get_flight_recorder()
        group = repr(self.group) if self.group is not None else "world"
        flightrec.lane(f"gather_launch:bucket{index}", bucket=index,
                       group=group, phase="launch")
        bucket = self.buckets[index]

        def job():
            fut.start_ns = time.perf_counter_ns()
            flightrec.lane(f"gather:bucket{index}", bucket=index,
                           group=group, phase="start")
            try:
                with RecordEvent(f"gather:bucket{index}"):
                    full = self._gather_full(bucket)
                    if hasattr(full, "block_until_ready"):
                        full.block_until_ready()
            except BaseException as e:   # surfaced at the wait
                fut._fail(e)
                flightrec.lane(f"gather:bucket{index}", bucket=index,
                               group=group, phase="error", error=repr(e))
            else:
                fut._resolve(full)
                flightrec.lane(f"gather:bucket{index}", bucket=index,
                               group=group, phase="end")
            fut.end_ns = time.perf_counter_ns()

        self._lane.submit(job)
        _m_gathers.labels(mode="prefetched").inc()
        return fut

    def ensure_gathered(self, index: int, _mode: str = "sync"):
        """Make bucket `index`'s full parameters resident (wait for the
        prefetch if one is in flight, else gather synchronously — fully
        exposed) and scatter them into the parameter views.

        The EXPOSED accounting covers the wait for the gathered data (the
        wire time forward actually blocks on — ~0 when the prefetch beat
        us here); the per-param scatter is compute-side materialization
        work both modes pay identically and is excluded."""
        from ...profiler import RecordEvent

        if self._state.get(index) == GATHERED:
            return
        t0 = time.perf_counter()
        fut = self._futures.get(index)
        if fut is not None:
            try:
                full = fut.wait()
            except BaseException:
                # a failed prefetch must not wedge the bucket INFLIGHT:
                # drop the future so a retry can gather fresh
                with self._lock:
                    self._futures.pop(index, None)
                    self._state[index] = SHARDED
                raise
        else:
            marker = RecordEvent(f"gather_launch:bucket{index}")
            marker.begin()
            marker.end()
            with RecordEvent(f"gather_sync:bucket{index}"):
                full = self._gather_full(self.buckets[index])
                if hasattr(full, "block_until_ready"):
                    full.block_until_ready()
            _m_gathers.labels(mode=_mode).inc()
        exposed = time.perf_counter() - t0
        self.exposed_gather_s += exposed
        self._pass_exposed_s += exposed
        # parameter mutation stays on the CALLING thread — the lane only
        # produces the flat buffer
        self._scatter_full(self.buckets[index], full)
        with self._lock:
            self._state[index] = GATHERED
            popped = self._futures.pop(index, None)
        # drop the flat gather buffer NOW (the scattered params are their
        # own buffers) so the watermark sees one bucket, not two
        if popped is not None:
            popped._value = None
        full = None
        _m_resident.set(len(self.resident_buckets()))
        obs_memory.sample_watermarks()

    def free_bucket(self, index: int):
        """Back to at-rest: drop the full parameter values of bucket
        `index` (the shard is the source of truth; forward never mutates
        parameters). Drains an in-flight prefetch first."""
        fut = self._futures.get(index)
        if fut is not None:
            fut._done.wait()
        with self._lock:
            self._futures.pop(index, None)
            self._state[index] = SHARDED
        self._free_params(self.buckets[index])
        _m_resident.set(len(self.resident_buckets()))
        obs_memory.sample_watermarks()

    def _gather_full(self, bucket):
        """All_gather this rank's shard into the padded full flat buffer.
        Rides the guarded collective layer (timeouts/retry/chaos apply);
        in single-process emulation the degenerate gather falls back to
        assembling from the host-side peer shards."""
        chunk = self._chunk(bucket)
        shard_t = Tensor(self._shards[bucket.index], _internal=True)
        gathered = _coll.all_gather(None, shard_t, group=self.group)
        full = gathered._value.reshape(-1)
        if int(full.shape[0]) == chunk * self.world:
            return full
        # emulation: the eager all_gather cloned the shard; peers are host.
        # Assemble on HOST and device_put ONCE — a device-side concatenate
        # would transiently hold parts + full (2 buckets) on top of the
        # previous bucket's scattered params, breaking the <= 2-bucket
        # residency the free-after-use discipline promises
        parts = [np.array(self._shards[bucket.index]) if r == self.rank
                 else self._peer_shards[bucket.index][r]
                 for r in range(self.world)]
        return jnp.asarray(np.concatenate(parts))

    def _scatter_full(self, bucket, full):
        for pi, off, n, shape in zip(bucket.param_indices, bucket.offsets,
                                     bucket.numels, bucket.shapes):
            p = self.params[pi]
            p._value = full[off:off + n].reshape(shape)

    def _fallback_read(self, index: int, pname: str, shape, dtype):
        """Self-healing path for a parameter read outside its layer's
        forward (FreedParamValue.materialize): exposed synchronous gather
        + scatter, returning this parameter's full device value. Counted
        (`mode="fallback"`) so undeclared external uses are visible in
        /metrics — declare them via register_external_use to prefetch."""
        self.ensure_gathered(index, _mode="fallback")
        b = self.buckets[index]
        for pi in b.param_indices:
            p = self.params[pi]
            if p.name == pname and tuple(p._value.shape) == tuple(shape):
                return p._value
        # name didn't resolve (unnamed params): fall back to the first
        # matching shape in the bucket
        for pi in b.param_indices:
            p = self.params[pi]
            if tuple(p._value.shape) == tuple(shape):
                return p._value
        raise RuntimeError(
            f"fallback gather of bucket {index} did not materialize a "
            f"parameter of shape {tuple(shape)} ({pname!r})")

    # ------------------------------------------------------- optimizer side
    def own_shard(self, index: int):
        """This rank's at-rest shard of bucket `index` (padded chunk)."""
        return self._shards[index]

    def peer_ranks(self) -> List[int]:
        return [r for r in range(self.world) if r != self.rank]

    def peer_shard(self, index: int, rank: int) -> np.ndarray:
        return self._peer_shards[index][rank]

    def commit_shard(self, index: int, new_shard):
        """Commit the optimizer's updated OWN shard (the at-rest value).
        Any gathered full copy of the bucket is now stale and is freed."""
        self._shards[index] = new_shard
        if self._state.get(index) == GATHERED:
            self.free_bucket(index)
        _m_param_bytes.set(self.param_bytes_per_rank())

    def commit_peer_shard(self, index: int, rank: int, new_shard):
        """Emulation only: the peer rank's updated shard (host-resident;
        np.array copies so no device buffer stays pinned)."""
        self._peer_shards[index][rank] = np.array(new_shard)

    # ------------------------------------------------------------ model side
    def register_external_use(self, layer, param):
        """Declare that `layer`'s forward reads `param` even though another
        layer owns it (tied weights). The bucket is then gathered by this
        layer's pre-hook instead of paying the fallback path."""
        self._external.setdefault(id(layer), []).append(param)

    def install_hooks(self, model, order=None):
        """Install the gather-ahead / free-after-use forward hooks.

        `order` (list of layers) defaults to registration order
        (pre-order traversal), which matches execution order for
        sequentially-built models; pass it explicitly when construction
        and execution order differ."""
        self.remove_hooks()
        if order is None:
            order = [l for _, l in model.named_sublayers(include_self=True)]
        param_ids = set(self._by_param)
        seq = []
        for layer in order:
            own = [p for p in layer._parameters.values()
                   if p is not None and id(p) in param_ids]
            own += [p for p in self._external.get(id(layer), [])
                    if id(p) in param_ids]
            if own:
                need = sorted({self._by_param[id(p)] for p in own})
                seq.append((layer, need))
        self._layer_order = seq
        # pass bracketing on the ROOT model (registered first/last so its
        # pre-hook runs before, and its post-hook after, any layer hook on
        # the same module): begin resets the per-pass use counts; end
        # frees leftovers and records the exposed-gather stats. Ending at
        # the last param-OWNING layer instead would free too early for a
        # root whose forward still reads a tied weight after its children.
        self._hook_handles.append(
            model.register_forward_pre_hook(self._pass_begin_hook))
        for k, (layer, _need) in enumerate(seq):
            self._hook_handles.append(
                layer.register_forward_pre_hook(self._make_pre_hook(k)))
            self._hook_handles.append(
                layer.register_forward_post_hook(self._make_post_hook(k)))
        self._hook_handles.append(
            model.register_forward_post_hook(self._pass_end_hook))
        return self

    def remove_hooks(self):
        for h in self._hook_handles:
            h.remove()
        self._hook_handles = []

    def _begin_pass(self):
        # self-heal a pass aborted by an exception: anything still
        # gathered from the previous attempt goes back to rest first
        for i in list(self.resident_buckets()):
            self.free_bucket(i)
        self._uses_left = {}
        for _layer, need in self._layer_order:
            for bi in need:
                self._uses_left[bi] = self._uses_left.get(bi, 0) + 1
        self._pass_exposed_s = 0.0
        self._pass_active = True

    def _end_pass(self):
        if self.free_after_use:
            for i in list(self.resident_buckets()):
                self.free_bucket(i)
        self._pass_active = False
        self.stats["exposed_gather_s_last_pass"] = self._pass_exposed_s
        _m_exposed.set(round(self._pass_exposed_s * 1e3, 6))

    def _pass_begin_hook(self, layer, inputs):
        if self.sharded:
            self._begin_pass()
        return None

    def _pass_end_hook(self, layer, inputs, outputs):
        if self.sharded and self._pass_active:
            self._end_pass()
        return None

    def _make_pre_hook(self, k: int):
        def hook(layer, inputs):
            if not self.sharded:
                return None
            from ...profiler import RecordEvent

            if not self._pass_active:
                # sublayer driven directly (no root call): self-arm
                self._begin_pass()
            marker = RecordEvent(f"zero3_prehook:layer{k}")
            marker.begin()
            marker.end()
            _layer, need = self._layer_order[k]
            for bi in need:
                self.ensure_gathered(bi)
            # the layer-ahead prefetch: enqueue the NEXT layers' buckets
            for j in range(k + 1, min(k + 1 + self.prefetch_ahead,
                                      len(self._layer_order))):
                for bi in self._layer_order[j][1]:
                    self.prefetch_bucket(bi)
            # marker: this layer's buckets are resident — its forward use
            # starts after this point (the span-ordering proof anchor)
            ready = RecordEvent(f"zero3_ready:layer{k}")
            ready.begin()
            ready.end()
            return None

        return hook

    def _make_post_hook(self, k: int):
        def hook(layer, inputs, outputs):
            if not self.sharded or not self._pass_active:
                return None
            _layer, need = self._layer_order[k]
            for bi in need:
                left = max(0, self._uses_left.get(bi, 0) - 1)
                self._uses_left[bi] = left
                if left == 0 and self.free_after_use:
                    self.free_bucket(bi)
            return None

        return hook

    @contextlib.contextmanager
    def materialize(self):
        """Temporarily gather EVERY bucket (full parameters resident) —
        for whole-model reads like `save_group_sharded_model`. Frees on
        all exits (analysis rule S001's contract)."""
        if not self.sharded:
            yield self
            return
        try:
            for b in self.buckets:
                self.ensure_gathered(b.index)
            yield self
        finally:
            for b in self.buckets:
                self.free_bucket(b.index)

    def unshard_(self):
        """Permanently leave stage-3: materialize the full parameters and
        drop the shards/hooks (the inverse of shard_())."""
        if not self.sharded:
            return self
        for b in self.buckets:
            self.ensure_gathered(b.index)
        self.remove_hooks()
        self.sharded = False
        self._shards.clear()
        self._peer_shards.clear()
        self._futures.clear()
        self._state.clear()
        _m_param_bytes.set(0)
        _m_resident.set(0)
        return self

    def reset_exposed(self):
        self.exposed_gather_s = 0.0

    # ------------------------------------------------------------ state io
    def state_dict(self) -> dict:
        """At-rest snapshot for sharded checkpoints: this rank's shards
        (plus the host-side peer shards under emulation) and the bucket
        key they were laid out under. Gathered copies are not saved — the
        shard is the source of truth."""
        out = {
            "bucket_key": self.comm._bucket_key,
            "rank": self.rank, "world": self.world,
            # unpadded bucket sizes: what reshard.py needs to strip the
            # world-N padding before re-chunking to a new world size
            "bucket_sizes": {int(b.index): int(b.size)
                             for b in self.buckets},
            "shards": {int(i): np.asarray(v)
                       for i, v in self._shards.items()},
        }
        if self.emulated:
            out["peer_shards"] = {
                int(i): {int(r): np.asarray(v) for r, v in peers.items()}
                for i, peers in self._peer_shards.items()}
        return out

    def load_state_dict(self, state: dict, allow_reshard: bool = False):
        """Restore a state_dict() snapshot into a freshly sharded store.
        The world size and bucket layout must match — a resume that
        re-bucketed differently would mis-slice every parameter. With
        ``allow_reshard=True`` a world-size drift triggers the elastic
        N→M transform (reshard.py) instead of refusing, provided the
        state carries the full shard set (the emulated peer-shard layout;
        a real per-rank state needs `CheckpointManager.load_sharded`,
        which joins every rank's file first)."""
        if int(state.get("world", self.world)) != self.world:
            if not allow_reshard:
                raise ValueError(
                    f"zero3 state world mismatch: checkpoint has "
                    f"{state.get('world')}, store runs {self.world}")
            from .reshard import reshard_zero3_states

            if not state.get("peer_shards"):
                raise ValueError(
                    f"zero3 state world mismatch (checkpoint "
                    f"{state.get('world')} vs live {self.world}) and this "
                    f"state holds only one rank's shards — reshard via "
                    f"CheckpointManager.load_sharded(allow_reshard=True), "
                    f"which joins all rank files")
            state = reshard_zero3_states([state], self.world)[0]
        key = state.get("bucket_key")
        if key is not None and self.comm._bucket_key is not None \
                and tuple(key) != tuple(self.comm._bucket_key):
            raise ValueError(
                "zero3 state bucket-key mismatch: the checkpointed bucket "
                "layout differs from this store's — resume with the same "
                "comm_buffer_size / parameter list")
        if not self.sharded:
            self.shard_()
        for i, v in (state.get("shards") or {}).items():
            self._shards[int(i)] = jnp.asarray(v)
        for i, peers in (state.get("peer_shards") or {}).items():
            self._peer_shards[int(i)] = {
                int(r): np.asarray(v) for r, v in peers.items()}
        # everything goes back to rest; stale gathered copies are freed
        for b in self.buckets:
            if self._state.get(b.index) == GATHERED:
                self.free_bucket(b.index)
        _m_param_bytes.set(self.param_bytes_per_rank())

    def meta_state(self) -> dict:
        """The layout fingerprint job_state carries (capture_job_state):
        enough to refuse a resume whose sharding geometry changed."""
        return {"world": self.world, "rank": self.rank,
                "n_buckets": len(self.buckets),
                "bucket_key": self.comm._bucket_key}

    def check_meta(self, meta: dict, allow_world_drift: bool = False):
        if int(meta.get("world", self.world)) != self.world:
            if not allow_world_drift:
                raise ValueError(
                    f"zero3 resume geometry mismatch: job_state world "
                    f"{meta.get('world')} vs live {self.world} — pass "
                    f"allow_reshard=True (restore_job_state) after "
                    f"resharding the shard payloads to accept the drift")
            # elastic resume across a world change: the shard payloads were
            # already resharded (reshard.py); the meta world is historical
            get_flight_recorder().note(
                "reshard", "world drift accepted on resume",
                from_world=int(meta.get("world", -1)), to_world=self.world)
        key = meta.get("bucket_key")
        if key is not None and self.comm._bucket_key is not None \
                and tuple(key) != tuple(self.comm._bucket_key):
            raise ValueError(
                "zero3 resume geometry mismatch: bucket layout changed "
                "between checkpoint and resume")

    def __repr__(self):
        return (f"Stage3ParamShards(rank={self.rank}/{self.world}, "
                f"buckets={len(self.buckets)}, sharded={self.sharded}, "
                f"resident={len(self.resident_buckets())})")


# ---------------------------------------------------------------------------
# measurement helper (tools/overlap_bench.py zero3 section + bench.py)
# ---------------------------------------------------------------------------

def _fake_params(shapes_dtypes, seed=0):
    rs = np.random.RandomState(seed)
    params = []
    for i, (shape, dt) in enumerate(shapes_dtypes):
        p = Tensor(rs.standard_normal(shape).astype(dt))
        p.stop_gradient = False
        p.name = f"p{i}"
        params.append(p)
    return params


def zero3_gather_report(params, config: Optional[GradCommConfig] = None,
                        world: int = 2, compute_s: float = 0.04,
                        seed: int = 0) -> dict:
    """Prefetched vs synchronous exposed-gather measurement for one
    model's parameters (host emulation — the same caveat as
    overlap_report: wall times are host assembly costs, not ICI transfer;
    the artifact records the STRUCTURE of the win). `params` provides
    shapes/dtypes only; detached fakes are sharded, so live models are
    never touched. `compute_s` is the emulated forward window the
    prefetches get to hide under, spread across the per-bucket steps."""
    config = config or GradCommConfig()
    shapes_dtypes = [(tuple(p._value.shape), np.dtype(p._value.dtype))
                     for p in params if not p.stop_gradient]

    # ---- synchronous: every gather fully exposed, one after another
    fakes = _fake_params(shapes_dtypes, seed=seed)
    store = Stage3ParamShards(fakes, GradCommunicator(config), rank=0,
                              world=world)
    store.shard_()
    per_bucket = []
    store.reset_exposed()
    for b in store.buckets:
        t0 = time.perf_counter()
        try:
            store.ensure_gathered(b.index)
            per_bucket.append({"bucket": b.index, "nbytes": int(b.nbytes),
                               "sync_ms": round(
                                   (time.perf_counter() - t0) * 1e3, 3)})
        finally:
            store.free_bucket(b.index)
    sync_exposed_ms = store.exposed_gather_s * 1e3
    bytes_per_rank = store.param_bytes_per_rank()
    param_bytes_full = int(store.stats["param_bytes_full"])
    n_buckets = len(store.buckets)

    # ---- prefetched: bucket k+1's gather launches before bucket k's
    # emulated compute window; only the first gather (and any prefetch
    # that outlives its window) is exposed
    fakes = _fake_params(shapes_dtypes, seed=seed)
    store2 = Stage3ParamShards(fakes, GradCommunicator(GradCommConfig(
        config.codec, config.comm_buffer_size,
        config.last_comm_buffer_size)), rank=0, world=world)
    store2.shard_()
    store2.reset_exposed()
    per_layer = compute_s / max(1, n_buckets)
    for i, b in enumerate(store2.buckets):
        try:
            store2.ensure_gathered(b.index)   # first: sync; later: waits
            if i + 1 < n_buckets:
                store2.prefetch_bucket(store2.buckets[i + 1].index)
            time.sleep(per_layer)             # the layer's compute window
        finally:
            store2.free_bucket(b.index)       # free after use
        for row in per_bucket:
            if row["bucket"] == b.index:
                row["prefetched"] = i > 0
    prefetch_exposed_ms = store2.exposed_gather_s * 1e3

    return {
        "world": int(world),
        "n_buckets": n_buckets,
        "param_bytes_full": param_bytes_full,
        "zero3_param_bytes_per_rank": int(bytes_per_rank),
        "sync_exposed_gather_ms": round(sync_exposed_ms, 3),
        "prefetch_exposed_gather_ms": round(prefetch_exposed_ms, 3),
        "emulated_forward_ms": round(compute_s * 1e3, 3),
        "per_bucket": per_bucket,
    }

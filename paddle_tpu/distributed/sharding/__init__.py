"""paddle.distributed.sharding — group-sharded (ZeRO) data parallelism.

Reference: python/paddle/distributed/sharding/group_sharded.py
(group_sharded_parallel / save_group_sharded_model) over the stage-2/3
modules (sharding_stage2.py:43, sharding_stage3.py:51).

TPU-native design: ZeRO levels become sharding *specifications* compiled by
GSPMD instead of runtime grad/param slicing modules —
  os      (stage 1): optimizer-state slots sharded over the 'sharding' axis
  os_g    (stage 2): + gradients (internal to the compiled step; XLA derives
                     the reduce-scatter from the slot/param shardings)
  p_g_os  (stage 3): + parameters themselves sharded
The compiled TrainStep reads these markers and lays out params/slots
accordingly; collectives ride ICI via pjit-inserted reduce_scatter/all_gather.

EAGER stage 3 (ISSUE 9): in a multi-rank eager world the `dist_spec`
annotation alone left every full parameter in HBM. `level="p_g_os"` now
also attaches a true at-rest store (`stage3.Stage3ParamShards` as
``model._zero3``): parameters live as 1/world shards, forward pre-hooks
prefetch each bucket's all_gather one layer ahead on a CollectiveLane,
post-hooks free after use, and `FusedFlatUpdater.step_sharded(...,
param_store=model._zero3)` updates the owned shard without ever
re-materializing the full parameter. See stage3.py for the lifetime
discipline.
"""
from __future__ import annotations

import contextlib

from jax.sharding import PartitionSpec as P

from .. import mesh as mesh_mod
from .stage3 import Stage3ParamShards

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "save_group_sharded_checkpoint", "Stage3ParamShards",
           "reshard"]


def __getattr__(name):
    if name == "reshard":  # lazy: keep the package import light
        import importlib

        return importlib.import_module(".reshard", __name__)
    raise AttributeError(name)

_LEVELS = ("os", "os_g", "p_g_os")
_MB_F = 1024.0 * 1024.0


def zero_slot_spec(shape, pspec, axis, deg):
    """ZeRO 1/2 optimizer-state sharding rule, shared by TrainStep's slot
    shardings and gpt_hbm_estimate's feasibility lowering: keep the param's
    own (tensor-parallel) spec and ADD `axis` on the first free divisible
    dim — the reference shards opt state across the sharding group
    regardless of mp (sharding_optimizer.py)."""
    if deg <= 1:
        return pspec
    entries = list(pspec) + [None] * (len(shape) - len(pspec))
    used = set()
    for e in entries:
        if e is None:
            continue
        used.update(e if isinstance(e, (tuple, list)) else (e,))
    if axis in used:
        return pspec
    for d, sdim in enumerate(shape):
        if entries[d] is None and sdim % deg == 0 and sdim >= deg:
            entries[d] = axis
            return P(*entries)
    return pspec


def _shard_spec_for(shape, axis, deg):
    for d, s in enumerate(shape):
        if s % deg == 0 and s >= deg:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return None


def group_sharded_parallel(model, optimizer, level, scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, overlap_comm=False,
                           fuse_update=False):
    """Wrap model+optimizer for ZeRO-style sharding at `level`.

    Net-new knobs (distributed/overlap.py + optimizer/fused.py):
    `overlap_comm` launches each grad bucket's reduce_scatter as backward
    completes it instead of one serial phase; `fuse_update` attaches a
    `FusedFlatUpdater` as `model._fused_update` so the weight update runs
    as one kernel per flat bucket (on the owned shard under stage >= 2 via
    its `step_sharded`)."""
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {_LEVELS}, got {level!r}")
    if offload:
        raise NotImplementedError(
            "offload=True (host-memory opt state) is not supported; TPU HBM "
            "sharding via level='p_g_os' is the equivalent lever")

    mesh = mesh_mod.get_mesh()
    axis = "sharding"
    deg = mesh_mod.axis_size(axis) if mesh is not None else 1

    # stage 1/2: shard optimizer slots even where params stay replicated
    optimizer._slot_shard_axis = axis

    if level in ("os_g", "p_g_os"):
        # stage >= 2 also shards the gradient reduction: attach a bucketed
        # grad communicator whose sync runs reduce_scatter + all_gather over
        # the sharding axis (grad_comm.py), so the eager multi-process path
        # has each rank reduce only its own shard — the compiled TrainStep
        # derives the same reduce_scatter from the slot shardings via GSPMD.
        # overlap_comm launches buckets mid-backward (distributed/overlap.py)
        from ..collective import new_group
        from ..grad_comm import GradCommConfig
        from ..overlap import communicator_for

        model._grad_comm = communicator_for(
            GradCommConfig(comm_buffer_size=buffer_max_size / _MB_F,
                           last_comm_buffer_size=max(
                               segment_size / _MB_F, 0.001),
                           overlap=overlap_comm),
            group=new_group(axes=(axis,)))
        if fuse_update:
            from ...optimizer.fused import FusedFlatUpdater

            model._fused_update = FusedFlatUpdater(
                optimizer, list(model.parameters()),
                communicator=model._grad_comm)

    if level == "p_g_os":
        if deg > 1:
            # compiled path: GSPMD placement markers (TrainStep lays the
            # parameters out sharded; XLA inserts the gathers)
            for p in model.parameters():
                if getattr(p, "dist_spec", None) is not None:
                    continue
                spec = _shard_spec_for(p._value.shape, axis, deg)
                if spec is not None:
                    p.dist_spec = spec
        from ..env import get_world_size

        eager_world = get_world_size()
        if eager_world > 1:
            # eager path: TRUE at-rest sharding (stage3.py) — parameters
            # become 1/world shards now; forward hooks gather/prefetch/free
            # per bucket, and step_sharded(param_store=) updates the shard
            store = Stage3ParamShards(
                [p for p in model.parameters() if not p.stop_gradient],
                communicator=model._grad_comm, world=eager_world,
                group=model._grad_comm.group)
            store.shard_()
            store.install_hooks(model)
            model._zero3 = store

    return model, optimizer, scaler


def save_group_sharded_checkpoint(model, root, step, optimizer=None,
                                  rank=None, world_size=None, barrier=None,
                                  manager=None, fs=None, fused=None,
                                  job_state=None, metadata=None):
    """Crash-safe sharded checkpoint for the DP/ZeRO path
    (robustness/checkpoint.py): each rank writes only its own shard into a
    shared temp directory; after the barrier, rank 0 verifies every shard's
    checksum and commits the manifest LAST, so the checkpoint becomes
    visible only when complete. A rank dying mid-write leaves the
    checkpoint invisible and `load_latest()` falls back to the previous
    valid one.

    `barrier` is the cross-rank sync callable (e.g. fleet barrier); in
    single-process/GSPMD tests it may be None. Returns the manager so the
    caller can load_latest()/gc() through the same layout.

    Stage 3: when the model carries a `_zero3` at-rest store, the model
    entry is the store's OWN-SHARD snapshot (``{"zero3": ...}``) — each
    rank persists exactly the 1/world it holds, never the gathered full
    parameters. Pass the `FusedFlatUpdater` as `fused=` to persist the
    shard-resident optimizer slots next to it (per-param
    ``optimizer.state_dict()`` never sees shard slots).
    """
    from ...robustness.checkpoint import CheckpointManager

    if rank is None or world_size is None:
        from .. import get_rank, get_world_size

        rank = get_rank() if rank is None else rank
        world_size = get_world_size() if world_size is None else world_size
    mgr = manager or CheckpointManager(root, fs=fs)
    store = getattr(model, "_zero3", None)
    if store is not None and store.sharded:
        payload = {"zero3": store.state_dict()}
    else:
        payload = {"model": model.state_dict()}
    if optimizer is not None:
        payload["optimizer"] = optimizer.state_dict()
    if fused is not None:
        payload["fused_shard_slots"] = fused.shard_slots_state()
    if job_state is not None:
        # job_state is RANK-LOCAL (per-rank rng streams, this rank's
        # error-feedback residuals), so it rides this rank's shard entry
        payload["job_state"] = job_state
    mgr.save_shard(payload, step, rank, world_size)
    if barrier is not None:
        barrier()
    if rank == 0:
        # metadata rides the manifest — a preemption emergency save tags
        # reason="preemption" here so retention GC exempts it
        mgr.finalize_sharded(step, world_size, metadata=metadata)
    return mgr


def save_group_sharded_model(model, output, optimizer=None):
    """Persist a group-sharded model as FULL (unsharded) weights.

    Reference semantics (group_sharded.py save_group_sharded_model): the
    stage-3 module gathers every sharded parameter before writing, so
    `model.pdparams` loads into a plain unsharded model. Under the eager
    at-rest store (`model._zero3`) `state_dict()` holds freed placeholders
    — writing those would either crash or persist garbage — so the store's
    `materialize()` window gathers all buckets around the save and frees
    them again on every exit. GSPMD-annotated jax.Arrays (compiled path)
    gather on host read automatically."""
    import os

    from ... import save as paddle_save

    os.makedirs(output, exist_ok=True)
    store = getattr(model, "_zero3", None)
    ctx = (store.materialize() if store is not None and store.sharded
           else contextlib.nullcontext())
    with ctx:
        paddle_save(model.state_dict(),
                    os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        paddle_save(optimizer.state_dict(),
                    os.path.join(output, "model.pdopt"))

"""Global device-mesh management.

The reference's communicator registries (platform/collective_helper.h: per-ring
NCCLCommContext) become ONE logical object on TPU: a jax.sharding.Mesh whose
named axes are the parallelism dimensions. Groups (collective.py) and the fleet
topology (fleet/base/topology.py analog) are views onto these axes; XLA emits
the matching ICI/DCN collectives from sharding specs.

Axis order follows the reference's hybrid topology
(fleet/base/topology.py:38): ["data", "pipe", "sharding", "sep", "model"].
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# canonical axis names, reference order topology.py:38 (+ net-new "sep")
AXIS_DATA = "data"
AXIS_PIPE = "pipe"
AXIS_SHARD = "sharding"
AXIS_SEP = "sep"
AXIS_MODEL = "model"
# expert parallelism (MoE): not part of the hybrid order — built
# explicitly via build_mesh({"expert": k, ...}). Declared HERE so every
# axis name the framework can route a collective over has one source of
# truth (rule X005 validates axis strings against these constants).
AXIS_EXPERT = "expert"
HYBRID_ORDER = [AXIS_DATA, AXIS_PIPE, AXIS_SHARD, AXIS_SEP, AXIS_MODEL]

_current: List[Optional[Mesh]] = [None]


def build_mesh(topology: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Create a Mesh from {axis: degree}. Missing hybrid axes get degree 1 and
    are dropped; axis order follows HYBRID_ORDER then any custom names."""
    devices = list(devices if devices is not None else jax.devices())
    names, dims = [], []
    for ax in HYBRID_ORDER:
        d = int(topology.get(ax, 1))
        if d > 1 or ax in topology:
            names.append(ax)
            dims.append(d)
    for ax, d in topology.items():
        if ax not in HYBRID_ORDER:
            names.append(ax)
            dims.append(int(d))
    total = int(np.prod(dims)) if dims else 1
    if total != len(devices):
        raise ValueError(
            f"mesh topology {dict(zip(names, dims))} needs {total} devices, "
            f"have {len(devices)}"
        )
    arr = np.array(devices).reshape(dims if dims else (1,))
    if not names:
        names = [AXIS_DATA]
    return Mesh(arr, tuple(names))


def set_mesh(mesh: Mesh):
    _current[0] = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _current[0]


def default_mesh() -> Mesh:
    """All devices on the data axis (pure DP)."""
    if _current[0] is None:
        set_mesh(build_mesh({AXIS_DATA: len(jax.devices())}))
    return _current[0]


def axis_size(axis: str) -> int:
    m = get_mesh()
    if m is None or axis not in m.axis_names:
        return 1
    return m.shape[axis]


def compat_shard_map(fn, mesh, in_specs, out_specs, check=False):
    """shard_map across jax versions: the top-level `jax.shard_map` (and its
    `check_vma` kwarg) only exists in newer jax; 0.4/0.5 spell it
    `jax.experimental.shard_map.shard_map(check_rep=...)`. `check` maps onto
    whichever replication-tracking kwarg the installed jax has; default off —
    most collective-bearing bodies manage their own replication (the 1F1B
    grad path is the exception, see pipeline.py)."""
    try:
        from jax import shard_map as sm

        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check)
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm

        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check)


def named_sharding(*spec) -> NamedSharding:
    return NamedSharding(default_mesh(), PartitionSpec(*spec))


def shard_tensor_value(val, spec: PartitionSpec):
    """Place a value onto the current mesh with the given PartitionSpec."""
    return jax.device_put(val, NamedSharding(default_mesh(), spec))


def sanitize_spec(spec: PartitionSpec, mesh: Optional[Mesh] = None) -> PartitionSpec:
    """Drop axis names not present in the mesh so model code can annotate the
    full hybrid spec [data, pipe, sharding, sep, model] unconditionally."""
    mesh = mesh or get_mesh()
    if mesh is None or spec is None:
        return spec or PartitionSpec()
    names = mesh.axis_names
    out = []
    for s in spec:
        if isinstance(s, str):
            out.append(s if s in names else None)
        elif isinstance(s, tuple):
            kept = tuple(a for a in s if a in names)
            out.append(kept if kept else None)
        else:
            out.append(s)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def manual_axis_names() -> set:
    """Axis names currently bound MANUALLY (inside a shard_map/pmap body):
    a sharding constraint over such an axis is invalid — the body already
    sees its per-device block — so constrain() drops them."""
    try:
        from jax._src import core as _core

        env = _core.get_axis_env()
        return set(getattr(env, "axis_sizes", {}) or {})
    except Exception:
        return set()


def constrain(tensor, *spec):
    """Sharding constraint on a Tensor while tracing under a mesh; no-op
    eagerly or without a mesh. Axes absent from the mesh — and axes the
    surrounding trace already maps manually (a shard_map body, e.g. the
    explicit-SPMD grad path of jit.TrainStep(grad_comm=)) — are dropped,
    so model code can annotate the full hybrid spec unconditionally."""
    m = get_mesh()
    if m is None:
        return tensor
    from ..framework.autograd import call_op
    from ..framework.tensor import Tensor

    if isinstance(tensor, Tensor) and not isinstance(tensor._value, jax.core.Tracer):
        return tensor
    clean = sanitize_spec(PartitionSpec(*spec), m)
    manual = manual_axis_names()
    if manual:
        drop = []
        for entry in clean:
            if isinstance(entry, str) and entry in manual:
                entry = None
            elif isinstance(entry, tuple):
                kept = tuple(a for a in entry if a not in manual)
                entry = kept if kept else None
            drop.append(entry)
        while drop and drop[-1] is None:
            drop.pop()
        clean = PartitionSpec(*drop)
        if not tuple(clean):
            return tensor   # nothing left to constrain inside the body
    sh = NamedSharding(m, clean)
    return call_op(lambda v: jax.lax.with_sharding_constraint(v, sh), tensor,
                   op_name="shard_constraint")

"""Launcher implementation (reference: fleet/launch.py + launch_utils.py)."""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "main", "watch_local_procs"]


def _parse_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch distributed training "
                    "(reference CLI: python -m paddle.distributed.launch)")
    parser.add_argument("--nnodes", type=str, default=None,
                        help="node count or range 'N' / 'N:M' (elastic)")
    parser.add_argument("--nproc_per_node", type=int, default=None,
                        help="processes per node (default: 1 — one process "
                             "drives all local TPU chips)")
    parser.add_argument("--ips", type=str, default="127.0.0.1",
                        help="comma-separated host list")
    parser.add_argument("--master", type=str, default=None,
                        help="coordination service address host:port")
    parser.add_argument("--rank", type=int, default=None,
                        help="node rank (defaults to POD_INDEX / 0)")
    parser.add_argument("--log_dir", type=str, default="log")
    parser.add_argument("--run_mode", type=str, default="collective",
                        choices=["collective", "ps"])
    parser.add_argument("--server_num", type=int, default=0)
    parser.add_argument("--worker_num", type=int, default=0)
    parser.add_argument("--heter_worker_num", type=int, default=0)
    parser.add_argument("--elastic_server", type=str, default=None,
                        help="etcd://host:port for elastic membership")
    parser.add_argument("--job_id", type=str, default="default")
    parser.add_argument("--devices", "--gpus", "--xpus", type=str,
                        default=None, dest="devices",
                        help="accepted for CLI parity; TPU chips are driven "
                             "by the mesh, not per-process pinning")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


def _build_env(rank, nranks, master, endpoints, base_env=None):
    """The PADDLE_TRAINER_* env protocol (launch_utils.py get_cluster)."""
    env = dict(base_env if base_env is not None else os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nranks),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_MASTER": master,
        "FLAGS_selected_tpus": "all",
    })
    return env


def _launch_elastic(args, node_ip, nproc):
    """Elastic mode (reference manager.py main loop): membership lives in
    etcd (--elastic_server etcd://host:port), endpoints derive from the
    observed member set, and scale events kill + relaunch the local
    workers with rewritten endpoints."""
    from ..fleet.elastic import ElasticController, ElasticManager
    from ..fleet.elastic.etcd_store import Etcd3GatewayStore

    store = Etcd3GatewayStore(args.elastic_server)
    mgr = ElasticManager(node_ip, str(args.nnodes or "1"), store=store,
                         job_id=args.job_id)
    os.makedirs(args.log_dir, exist_ok=True)
    lifes = [0]

    def launch_fn(node_eps):
        hosts = [e.rsplit(":", 1)[0] for e in node_eps]
        if node_ip not in hosts:
            # our own registration hasn't landed in the store yet (e.g.
            # transient put failure at startup, heartbeat will retry):
            # tell the controller to hold, not crash
            return None
        endpoints = [f"{h}:{8091 + j}" for h in hosts for j in range(nproc)]
        master = f"{hosts[0]}:8090"
        node_rank = hosts.index(node_ip)
        lifes[0] += 1
        procs = []
        for local in range(nproc):
            rank = node_rank * nproc + local
            env = _build_env(rank, len(endpoints), master, endpoints)
            # the child dups the fd at spawn; closing the parent's handle
            # immediately avoids leaking one per worker per life
            with open(os.path.join(
                    args.log_dir,
                    f"workerlog.{local}.life{lifes[0]}"), "w") as lf:
                procs.append(subprocess.Popen(
                    [sys.executable, "-u", args.training_script,
                     *args.training_script_args],
                    env=env, stdout=lf, stderr=lf))
        return procs

    return ElasticController(mgr, launch_fn).run()


def watch_local_procs(procs, log_files=None):
    """Watchdog (launch_utils.py watch_local_trainers): if any proc exits
    non-zero, terminate the rest and propagate the failure."""
    try:
        while True:
            alive = False
            for i, p in enumerate(procs):
                ret = p.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    for q in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    return ret
            if not alive:
                return 0
            time.sleep(1)
    except KeyboardInterrupt:
        for q in procs:
            if q.poll() is None:
                q.send_signal(signal.SIGTERM)
        return 1


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_ps(args, ips):
    """PS-mode launcher (reference: fleet launch_ps / launch_utils
    get_ps_cluster): spawn --server_num PSERVER processes and --worker_num
    TRAINER processes on this node, wiring the PADDLE_PSERVERS_IP_PORT_LIST
    / TRAINING_ROLE env protocol the role makers read."""
    n_servers = int(args.server_num or 1)
    n_workers = int(args.worker_num or 1)
    n_heter = int(args.heter_worker_num or 0)
    host = ips[0] if ips else "127.0.0.1"
    server_eps = [f"{host}:{_free_port()}" for _ in range(n_servers)]
    heter_eps = [f"{host}:{_free_port()}" for _ in range(n_heter)]

    os.makedirs(args.log_dir, exist_ok=True)
    procs, logs = [], []

    def spawn(role, idx, extra_env):
        env = dict(os.environ)
        env.update({
            "PADDLE_PSERVERS_IP_PORT_LIST": ",".join(server_eps),
            "PADDLE_HETER_TRAINER_IP_PORT_LIST": ",".join(heter_eps),
            "PADDLE_TRAINERS_NUM": str(n_workers),
            "TRAINING_ROLE": role,
            **extra_env,
        })
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        lf = open(os.path.join(args.log_dir,
                               f"{role.lower()}log.{idx}"), "w")
        logs.append(lf)
        procs.append(subprocess.Popen(cmd, env=env, stdout=lf, stderr=lf))

    for i, ep in enumerate(server_eps):
        spawn("PSERVER", i, {"PADDLE_PORT": ep.rsplit(":", 1)[1],
                             "POD_IP": host,
                             "PADDLE_PSERVER_ID": str(i),
                             "PADDLE_TRAINER_ID": str(i)})
    server_procs = procs[:]
    procs_before = len(procs)
    for i in range(n_workers):
        spawn("TRAINER", i, {"PADDLE_TRAINER_ID": str(i)})
    # heterogeneous device workers (reference: launch_utils
    # get_heter_worker_endpoints + TRAINING_ROLE=HETER_TRAINER)
    for i in range(n_heter):
        spawn("HETER_TRAINER", i, {
            "PADDLE_TRAINER_ID": str(i),
            "PADDLE_PORT": heter_eps[i].rsplit(":", 1)[1],
        })
    trainer_procs = procs[procs_before:]
    # servers park in run_server(); watch the trainers, then retire servers
    # (reference watch_local_trainers semantics)
    ret = watch_local_procs(trainer_procs)
    for p in server_procs:
        if p.poll() is None:
            p.terminate()
    for lf in logs:
        lf.close()
    return ret


def launch(args=None):
    args = args if args is not None else _parse_args()
    ips = [h for h in args.ips.split(",") if h]
    # --nnodes N (or elastic "N:M": use the floor) overrides the ip-list size,
    # for clusters where each node runs the launcher with its own --rank
    nnodes = (int(str(args.nnodes).split(":")[0]) if args.nnodes
              else len(ips))
    if len(ips) < nnodes:
        ips = ips + [ips[0]] * (nnodes - len(ips))
    node_rank = args.rank
    if node_rank is None:
        node_rank = int(os.environ.get("POD_INDEX",
                                       os.environ.get("PADDLE_TRAINER_ID", 0)))
    nproc = args.nproc_per_node or 1
    master = args.master or f"{ips[0]}:8090"

    if args.run_mode == "ps":
        return _launch_ps(args, ips)

    if args.elastic_server:
        return _launch_elastic(args, ips[min(node_rank, len(ips) - 1)],
                               nproc)

    nranks = nnodes * nproc
    endpoints = []
    for ip in ips:
        for j in range(nproc):
            endpoints.append(f"{ip}:{8091 + j}")

    os.makedirs(args.log_dir, exist_ok=True)
    procs, logs = [], []
    for local in range(nproc):
        rank = node_rank * nproc + local
        env = _build_env(rank, nranks, master, endpoints)
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        lf = open(os.path.join(args.log_dir, f"workerlog.{local}"), "w")
        logs.append(lf)
        procs.append(subprocess.Popen(cmd, env=env, stdout=lf, stderr=lf)
                     if nproc > 1 or nnodes > 1 else
                     subprocess.Popen(cmd, env=env))
    ret = watch_local_procs(procs)
    for lf in logs:
        lf.close()
    return ret


def main():
    sys.exit(launch() or 0)


if __name__ == "__main__":
    main()

"""python -m paddle_tpu.distributed.launch — multi-process/multi-host launcher.

Reference: fleet/launch.py:508 (launch_collective:370) + launch_utils.py pod/
trainer env assembly (PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT /
PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS protocol).

TPU-native: one process per *host* (not per chip — a process drives all its
local chips through the mesh), rendezvous via the PJRT coordination service
(jax.distributed), TPU topology discovered from the environment. The same env
protocol is emitted so role makers and user scripts keep working.
"""
from .main import launch, main  # noqa: F401

"""DataParallel (reference: python/paddle/fluid/dygraph/parallel.py:397 +
C++ Reducer, imperative/reducer.cc).

TPU-native: no Reducer — gradients are averaged by the compiler. Under the
sharded TrainStep the batch is sharded over the 'data' mesh axis and GSPMD
inserts the gradient AllReduce; in eager multi-process mode (multi-host CPU
testing), grads are synced explicitly after backward via psum.
"""
from __future__ import annotations

import numpy as np

from ..nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        from .grad_comm import GradCommConfig

        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self.group = group
        # per-instance strategy wins over the fleet-global one (reference:
        # the legacy DataParallel(strategy=...) arg)
        self._strategy = strategy
        # validate the bucketing knobs here (GradCommConfig owns the rule)
        # so a bad value fails at construction, not at the first sync
        GradCommConfig(comm_buffer_size=comm_buffer_size,
                       last_comm_buffer_size=last_comm_buffer_size)
        self.comm_buffer_size = float(comm_buffer_size)
        self.last_comm_buffer_size = float(last_comm_buffer_size)
        self._grad_comm = None
        self._grad_comm_key = None

    def forward(self, *inputs, **kwargs):
        out = self._layers(*inputs, **kwargs)
        # overlapped grad sync (grad_comm_configs["overlap"]): arm the
        # upcoming backward — grad-ready hooks launch each bucket's
        # collective the moment its last grad lands, and the
        # apply_collective_grads() below becomes the flush barrier
        from .env import get_world_size

        world = get_world_size()
        if world > 1:
            comm = self._grad_communicator()
            if hasattr(comm, "prepare"):
                comm.prepare([p for p in self._layers.parameters()
                              if not p.stop_gradient], world=world)
        return out

    def scale_loss(self, loss):
        # grad averaging is done by the compiler / explicit psum; loss unscaled
        return loss

    def apply_collective_grads(self):
        from .env import get_world_size

        if get_world_size() <= 1:
            return
        # reference Reducer semantics (imperative/reducer.cc): every
        # trainable param must produce a grad unless find_unused_parameters
        # marks absent ones ready (here: zero-filled so the collective still
        # matches across ranks); without the flag, missing grads are a hard
        # error — the reference build would hang in the allreduce
        missing = [p for p in self._layers.parameters()
                   if not p.stop_gradient and p.grad is None]
        if missing:
            if not self.find_unused_parameters:
                names = [p.name for p in missing[:8]]
                raise RuntimeError(
                    f"{len(missing)} parameter(s) produced no gradient this "
                    f"step (e.g. {names}); ranks would desync in the grad "
                    f"allreduce. Pass find_unused_parameters=True to "
                    f"DataParallel if parts of the model are conditionally "
                    f"unused.")
            from ..framework.tensor import Tensor

            for p in missing:
                p.grad = Tensor(np.zeros(p.shape,
                                         dtype=np.dtype(p._value.dtype)))
        # bucketed sync (reference Reducer groups, imperative/reducer.cc):
        # grads coalesce into ~comm_buffer_size MB flat buffers and one
        # collective runs per bucket instead of per parameter. The wire
        # codec comes from the strategy: grad_comm_configs when the
        # grad_comm toggle is on (bf16 default, fp32 escape hatch, int8
        # quantized with error feedback), else bf16 iff fp16_allreduce
        # (meta_optimizers/fp16_allreduce_optimizer.py — bf16 is the TPU
        # half format, exponent-safe), else the grads' own dtype. The
        # per-instance strategy arg wins; else the fleet-global one.
        comm = self._grad_communicator()
        comm.sync([p for p in self._layers.parameters()
                   if not p.stop_gradient], world=get_world_size())

    def _grad_communicator(self):
        from .fleet import _fleet_state
        from .grad_comm import config_from_strategy
        from .overlap import communicator_for

        st = (self._strategy if self._strategy is not None
              else _fleet_state.get("strategy"))
        cfg = config_from_strategy(st, self.comm_buffer_size,
                                   self.last_comm_buffer_size)
        key = (cfg.codec, cfg.comm_buffer_size, cfg.last_comm_buffer_size,
               cfg.error_feedback, cfg.overlap)
        if self._grad_comm is None or key != self._grad_comm_key:
            self._grad_comm = communicator_for(cfg, group=self.group)
            self._grad_comm_key = key
        return self._grad_comm

    # transparent passthrough of module protocol
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    set_dict = set_state_dict
    load_dict = set_state_dict

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

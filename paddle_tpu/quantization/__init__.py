"""Quantization — QAT + PTQ.

Parity: contrib/slim/quantization (ImperativeQuantAware for
quantization-aware training, PostTrainingQuantization for post-training
calibration). TPU-native: fake-quant is a straight-through-estimator op that
XLA fuses into the surrounding matmul; int8 deployment export writes scales
alongside weights (TPUs execute int8 via XLA's native quantized convs when
available, bf16 otherwise).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..framework.autograd import call_op as op
from ..framework.tensor import Tensor

__all__ = [
    "quant_abs_max", "fake_quant_dequant", "FakeQuantAbsMax",
    "QuantedLinear", "QuantedConv2D", "ImperativeQuantAware",
    "PostTrainingQuantization",
]


def quant_abs_max(x, bits=8):
    """Symmetric abs-max scale."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return float(jnp.abs(xv).max()) / (2 ** (bits - 1) - 1)


def _fq_kernel(x, scale, bits):
    qmax = 2 ** (bits - 1) - 1
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s), -qmax, qmax)
    # straight-through estimator: forward quantizes, backward is identity
    return x + jax.lax.stop_gradient(q * s - x)


def fake_quant_dequant(x, scale=None, bits=8):
    """fake_quantize_dequantize op (operators/fake_quantize_op.*) with STE."""
    if scale is None:
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        scale = jnp.abs(jax.lax.stop_gradient(xv)).max() / (2 ** (bits - 1) - 1)
    return op(_fq_kernel, x, scale=scale, bits=bits,
              op_name="fake_quantize_dequantize")


class FakeQuantAbsMax(nn.Layer):
    """Activation fake-quant with a running abs-max (moving-average observer,
    slim/quantization MovingAverageAbsMaxScale analog)."""

    def __init__(self, bits=8, momentum=0.9):
        super().__init__()
        self.bits = bits
        self.momentum = momentum
        self.register_buffer("scale", Tensor(jnp.zeros(()), _internal=True))
        self._seen = False

    def forward(self, x):
        if self.training:
            cur = jnp.abs(jax.lax.stop_gradient(x._value)).max() / (
                2 ** (self.bits - 1) - 1)
            prev = self.scale._value
            new = jnp.where(prev > 0,
                            self.momentum * prev + (1 - self.momentum) * cur,
                            cur)
            self.scale._value = new
        return fake_quant_dequant(x, self.scale._value, self.bits)


class QuantedLinear(nn.Layer):
    """Linear with fake-quantized weights + activations."""

    def __init__(self, layer, weight_bits=8, activation_bits=8):
        super().__init__()
        self.inner = layer
        self.weight_bits = weight_bits
        self.act_quant = FakeQuantAbsMax(activation_bits)

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        x = self.act_quant(x)
        w = fake_quant_dequant(self.inner.weight, bits=self.weight_bits)
        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(nn.Layer):
    def __init__(self, layer, weight_bits=8, activation_bits=8):
        super().__init__()
        self.inner = layer
        self.weight_bits = weight_bits
        self.act_quant = FakeQuantAbsMax(activation_bits)

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        x = self.act_quant(x)
        w = fake_quant_dequant(self.inner.weight, bits=self.weight_bits)
        return F.conv2d(x, w, self.inner.bias,
                        stride=self.inner._stride,
                        padding=self.inner._padding,
                        dilation=self.inner._dilation,
                        groups=self.inner._groups)


class Int8Linear(nn.Layer):
    """Weight-only int8 SERVING Linear: weights stored int8 + per-channel
    scales, matmul through the pallas quant kernel (ops/quant_matmul.py).
    This is the deployment form a QAT/PTQ Linear converts to — halved
    weight bytes is the memory-bound inference win on TPU."""

    def __init__(self, layer, stochastic=False, seed=None):
        super().__init__()
        import jax.numpy as jnp

        from ..ops.quant_matmul import quantize_int8, stable_seed

        if seed is None:
            # per-layer seed derived from the WEIGHT NAME via crc32 —
            # stable across processes and runs (the salted builtin hash()
            # is not), so every SPMD rank and every reload quantizes to
            # the same int8 bits (ISSUE 13 determinism contract)
            seed = stable_seed(getattr(layer.weight, "name", "") or "")
        q, s = quantize_int8(layer.weight._value.astype(jnp.float32),
                             stochastic=stochastic, seed=seed)
        from ..framework.tensor import Tensor

        self.qweight = Tensor(q, _internal=True)
        self.scales = Tensor(s, _internal=True)
        self.bias = layer.bias
        self.out_features = int(layer.weight.shape[1])

    def forward(self, x):
        from ..framework.tensor import Tensor
        from ..ops.quant_matmul import quant_matmul

        xv = x._value if isinstance(x, Tensor) else x
        shape = xv.shape
        out = quant_matmul(xv.reshape(-1, shape[-1]), self.qweight._value,
                           self.scales._value, out_dtype=xv.dtype)
        out = out.reshape(shape[:-1] + (out.shape[-1],))
        if self.bias is not None:
            out = out + self.bias._value
        t = Tensor(out, _internal=True)
        t.stop_gradient = True  # serving-only layer (weights are int8)
        return t


def convert_to_int8(model, stochastic=False):
    """Swap every nn.Linear for an Int8Linear (serving conversion — the
    reference's save-quantized-model step). Each layer quantizes under
    its own name-derived deterministic seed."""
    for name, sub in model.named_sublayers(include_self=False):
        for cname, child in getattr(sub, "_sub_layers", {}).items():
            if type(child).__name__ == "Linear":
                sub._sub_layers[cname] = Int8Linear(child,
                                                    stochastic=stochastic)
    for cname, child in getattr(model, "_sub_layers", {}).items():
        if type(child).__name__ == "Linear":
            model._sub_layers[cname] = Int8Linear(child,
                                                  stochastic=stochastic)
    return model


_QUANTABLE = {"Linear": QuantedLinear, "Conv2D": QuantedConv2D}


class ImperativeQuantAware:
    """QAT rewriter (slim/quantization/imperative/qat.py): swaps Linear/Conv2D
    sublayers for fake-quantized twins in place."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 quantizable_layer_type=("Conv2D", "Linear"), **kw):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.types = set(quantizable_layer_type)

    def quantize(self, model):
        self._rewrite(model)
        return model

    def _rewrite(self, layer):
        for name, sub in list(layer._sub_layers.items()):
            cls = type(sub).__name__
            if cls in self.types and cls in _QUANTABLE:
                layer._sub_layers[name] = _QUANTABLE[cls](
                    sub, self.weight_bits, self.activation_bits)
            else:
                self._rewrite(sub)

    def save_quantized_model(self, model, path, input_spec=None):
        from .. import jit

        jit.save(model, path, input_spec=input_spec)


class PostTrainingQuantization:
    """PTQ calibrator: run calibration batches, observe abs-max activation
    scales per quantable layer, emit a scale table + quantized state dict
    (slim/quantization/post_training_quantization.py analog)."""

    def __init__(self, model, data_loader=None, batch_nums=10, bits=8,
                 algo="abs_max", hist_percent=0.99999, bins=2048):
        from .observers import make_observer

        self.model = model
        self.data_loader = data_loader
        self.batch_nums = batch_nums
        self.bits = bits
        self.algo = algo
        self._mk_observer = lambda: make_observer(
            algo, percent=hist_percent, bins=bins,
            quant_levels=2 ** (bits - 1) - 1)
        self.act_scales = {}
        self.weight_scales = {}
        self._observers = {}

    def quantize(self):
        import numpy as np

        hooks = []
        observers = self._observers

        def make_hook(name):
            def hook(layer, inputs, output):
                val = output._value if isinstance(output, Tensor) else output
                obs = observers.get(name)
                if obs is None:
                    obs = observers[name] = self._mk_observer()
                obs.update(np.asarray(val))

            return hook

        for name, sub in self.model.named_sublayers():
            if type(sub).__name__ in ("Linear", "Conv2D"):
                hooks.append(sub.register_forward_post_hook(make_hook(name)))
        self.model.eval()
        try:
            if self.data_loader is not None:
                for i, batch in enumerate(self.data_loader):
                    if i >= self.batch_nums:
                        break
                    xs = batch[0] if isinstance(batch, (tuple, list)) else batch
                    self.model(xs)
        finally:
            for h in hooks:
                h.remove()
        qmax = 2 ** (self.bits - 1) - 1
        self.act_scales = {name: obs.threshold() / qmax
                           for name, obs in self._observers.items()}
        for name, sub in self.model.named_sublayers():
            if type(sub).__name__ in ("Linear", "Conv2D"):
                self.weight_scales[name] = quant_abs_max(sub.weight,
                                                         self.bits)
        return self.model

    def save_quantized_model(self, save_model_path, **kw):
        import json
        import os

        os.makedirs(save_model_path, exist_ok=True)
        from .. import save as paddle_save

        paddle_save(self.model.state_dict(),
                    os.path.join(save_model_path, "model.pdparams"))
        with open(os.path.join(save_model_path, "quant_scales.json"),
                  "w") as f:
            json.dump({"bits": self.bits, "activations": self.act_scales,
                       "weights": self.weight_scales}, f, indent=2)

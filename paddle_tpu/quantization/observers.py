"""Calibration observers for post-training quantization.

Reference: slim/quantization/post_training_quantization.py supports
abs_max / moving-average / histogram-percentile / KL / MSE activation
calibration (`algo=` in PostTrainingQuantization). Same surface here, as
small host-side observers — calibration is streaming numpy work; the
resulting scales feed the int8 pallas serving path (ops/quant_matmul).
"""
from __future__ import annotations

import numpy as np

__all__ = ["AbsMaxObserver", "AvgObserver", "HistObserver", "KLObserver",
           "MSEObserver", "make_observer"]


class AbsMaxObserver:
    """Running max of |x| (algo='abs_max')."""

    def __init__(self, **kw):
        self.stat = 0.0

    def update(self, arr: np.ndarray):
        self.stat = max(self.stat, float(np.abs(arr).max(initial=0.0)))

    def threshold(self) -> float:
        return self.stat or 1e-8


class AvgObserver(AbsMaxObserver):
    """Average of per-batch abs-max (algo='avg')."""

    def __init__(self, **kw):
        self.vals = []

    def update(self, arr):
        self.vals.append(float(np.abs(arr).max(initial=0.0)))

    def threshold(self):
        return float(np.mean(self.vals)) if self.vals else 1e-8


class _HistogramObserver:
    """Shared |x| histogram with dynamic range growth: when a batch exceeds
    the current range, old counts rebin into the widened range."""

    def __init__(self, bins=2048, **kw):
        self.bins = int(bins)
        self.hist = np.zeros(self.bins, np.float64)
        self.hi = 0.0

    def update(self, arr):
        a = np.abs(np.asarray(arr, np.float64)).reshape(-1)
        mx = float(a.max(initial=0.0))
        if mx == 0.0:
            return
        if mx > self.hi:
            if self.hi > 0.0:
                # rebin old counts into the widened range
                ratio = self.hi / mx
                old_edges = np.linspace(0, ratio * self.bins, self.bins + 1)
                new_counts = np.zeros(self.bins, np.float64)
                for i in range(self.bins):
                    lo, hi2 = old_edges[i], old_edges[i + 1]
                    li, ri = int(lo), min(int(np.ceil(hi2)), self.bins)
                    for j in range(li, ri):
                        ov = max(0.0, min(hi2, j + 1) - max(lo, j))
                        new_counts[j] += self.hist[i] * (
                            ov / (hi2 - lo) if hi2 > lo else 0.0)
                self.hist = new_counts
            self.hi = mx
        idx = np.minimum((a / self.hi * self.bins).astype(np.int64),
                         self.bins - 1)
        np.add.at(self.hist, idx, 1.0)


class HistObserver(_HistogramObserver):
    """Percentile of the |x| histogram (algo='hist'): clip the tail so
    outliers don't blow the scale."""

    def __init__(self, bins=2048, percent=0.99999, **kw):
        super().__init__(bins)
        self.percent = float(percent)

    def threshold(self):
        total = self.hist.sum()
        if total == 0:
            return 1e-8
        cum = np.cumsum(self.hist) / total
        idx = int(np.searchsorted(cum, self.percent))
        return (idx + 0.5) / self.bins * self.hi or 1e-8


class KLObserver(_HistogramObserver):
    """KL-divergence threshold search (algo='KL'; the TensorRT calibration
    scheme the reference's cal_kl_threshold implements): pick the clip
    that minimizes KL(P || quantized Q)."""

    def __init__(self, bins=2048, quant_levels=128, **kw):
        super().__init__(bins)
        self.levels = int(quant_levels)

    def threshold(self):
        hist = self.hist
        if hist.sum() == 0:
            return 1e-8
        best_i, best_kl = self.bins, np.inf
        for i in range(self.levels, self.bins + 1, 16):
            p = hist[:i].copy()
            p[-1] += hist[i:].sum()          # outliers clip into the edge
            if p.sum() == 0:
                continue
            # quantize the i bins down to `levels`, then expand back
            factor = i / self.levels
            q = np.zeros(i, np.float64)
            for lv in range(self.levels):
                lo = int(np.floor(lv * factor))
                hi2 = max(int(np.ceil((lv + 1) * factor)), lo + 1)
                chunk = hist[lo:min(hi2, i)]
                nz = (chunk > 0).sum()
                if nz:
                    q[lo:min(hi2, i)] = np.where(chunk > 0,
                                                 chunk.sum() / nz, 0.0)
            pm = p / p.sum()
            qs = q.sum()
            if qs == 0:
                continue
            qm = q / qs
            mask = pm > 0  # KL only over occupied bins (no 0*log(0) noise)
            kl = float(np.sum(
                pm[mask] * np.log(pm[mask] / np.maximum(qm[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_i = kl, i
        return (best_i + 0.5) / self.bins * self.hi or 1e-8


class MSEObserver(_HistogramObserver):
    """Clip-ratio search minimizing expected quantization MSE over the
    observed |x| histogram (algo='mse')."""

    def __init__(self, bins=2048, quant_levels=127, steps=40, **kw):
        super().__init__(bins)
        self.levels = int(quant_levels)
        self.steps = int(steps)

    def threshold(self):
        if self.hist.sum() == 0:
            return 1e-8
        centers = (np.arange(self.bins) + 0.5) / self.bins * self.hi
        w = self.hist
        best_t, best_err = self.hi, np.inf
        # log-spaced candidates: with heavy outliers the optimal clip can
        # sit orders of magnitude below the observed max
        for r in np.logspace(-3, 0, self.steps):
            t = r * self.hi
            scale = t / self.levels
            q = np.clip(np.round(centers / scale), 0, self.levels) * scale
            err = float((w * (centers - q) ** 2).sum())
            if err < best_err:
                best_err, best_t = err, t
        return best_t or 1e-8


_ALGOS = {"abs_max": AbsMaxObserver, "avg": AvgObserver,
          "hist": HistObserver, "KL": KLObserver, "kl": KLObserver,
          "mse": MSEObserver}


def make_observer(algo: str, **kw):
    try:
        return _ALGOS[algo](**kw)
    except KeyError:
        raise ValueError(f"unknown PTQ algo {algo!r}; one of "
                         f"{sorted(set(_ALGOS))}") from None

"""einsum (reference: python/paddle/tensor/einsum.py) — jnp.einsum hits the MXU
directly via dot_general, no custom planner needed."""
from __future__ import annotations

import jax.numpy as jnp

from ._helpers import Tensor, op


def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return op(lambda *vs: jnp.einsum(equation, *vs), *operands, op_name="einsum")

"""Linear algebra ops (reference: python/paddle/tensor/linalg.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._helpers import Tensor, op, val
from .math import bmm, dot, matmul, mm, mv  # noqa: F401 - re-exported


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def fn(v):
        if axis is None:
            flat = v.reshape(-1)
            if p in ("fro", 2):
                return jnp.sqrt(jnp.sum(flat * flat))
            if p == 1:
                return jnp.sum(jnp.abs(flat))
            if p == np.inf or p == float("inf"):
                return jnp.max(jnp.abs(flat))
            if p == -np.inf or p == float("-inf"):
                return jnp.min(jnp.abs(flat))
            return jnp.sum(jnp.abs(flat) ** p) ** (1.0 / p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro":
            return jnp.sqrt(jnp.sum(v * v, axis=ax, keepdims=keepdim))
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(v) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return op(fn, x, op_name="norm")


def dist(x, y, p=2, name=None):
    def fn(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype))
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)

    return op(fn, x, y, op_name="dist")


def cond(x, p=None, name=None):
    return op(lambda v: jnp.linalg.cond(v, p=p), x)


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else _first_dim3(x)
    return op(lambda a, b: jnp.cross(a, b, axis=ax), x, y, op_name="cross")


def _first_dim3(x):
    for i, s in enumerate(x.shape):
        if s == 3:
            return i
    return -1


def cholesky(x, upper=False, name=None):
    def fn(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return op(fn, x, op_name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)

    return op(fn, x, y)


def qr(x, mode="reduced", name=None):
    outs = op(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), x, op_name="qr")
    return outs


def svd(x, full_matrices=False, name=None):
    outs = op(lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)), x, op_name="svd")
    return outs


def eig(x, name=None):
    w, v = np.linalg.eig(x.numpy())
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    outs = op(lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), x, op_name="eigh")
    return outs


def eigvals(x, name=None):
    return Tensor(np.linalg.eigvals(x.numpy()))


def eigvalsh(x, UPLO="L", name=None):
    return op(lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), x)


def inv(x, name=None):
    return op(jnp.linalg.inv, x, op_name="inv")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return op(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), x)


def solve(x, y, name=None):
    return op(jnp.linalg.solve, x, y, op_name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return op(fn, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = np.linalg.lstsq(x.numpy(), y.numpy(), rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(np.asarray(rank)), Tensor(sv)


def lu(x, pivot=True, get_infos=False, name=None):
    lu_mat, piv = jax.scipy.linalg.lu_factor(x._value)
    outs = [Tensor(lu_mat, _internal=True), Tensor((piv + 1).astype("int32"), _internal=True)]
    if get_infos:
        outs.append(Tensor(jnp.zeros((), "int32"), _internal=True))
    return tuple(outs)


def matrix_power(x, n, name=None):
    return op(lambda v: jnp.linalg.matrix_power(v, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return op(lambda v: jnp.linalg.matrix_rank(v, rtol=tol).astype("int64"), x)


def det(x, name=None):
    return op(jnp.linalg.det, x, op_name="det")


def slogdet(x, name=None):
    def fn(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])

    return op(fn, x)


def multi_dot(x, name=None):
    return op(lambda *vs: jnp.linalg.multi_dot(vs), *x)


def histogram(input, bins=100, min=0, max=0, name=None):
    arr = input.numpy()
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    hist, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return Tensor(hist.astype(np.int64))


def bincount(x, weights=None, minlength=0, name=None):
    if weights is not None:
        return op(
            lambda v, w: jnp.bincount(v, weights=w, minlength=minlength,
                                      length=int(np.maximum(x.numpy().max(initial=0) + 1, minlength))),
            x,
            weights,
        )
    n = int(np.maximum(x.numpy().max(initial=0) + 1, minlength))
    return op(lambda v: jnp.bincount(v, minlength=minlength, length=n), x)


def corrcoef(x, rowvar=True, name=None):
    return op(lambda v: jnp.corrcoef(v, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return op(lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0), x)


# ----------------------- linalg tail (reference paddle.linalg surface)

def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """Unpack combined LU into (P, L, U) (reference lu_unpack)."""
    def fn(lu_v, piv):
        m, n = lu_v.shape[-2], lu_v.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_v[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_v.dtype)
        U = jnp.triu(lu_v[..., :k, :])
        # pivots (1-based sequential swaps) → permutation matrix
        perm = jnp.arange(m)
        def swap(p, i):
            j = piv[i] - 1
            a, b = p[i], p[j]
            return p.at[i].set(b).at[j].set(a), None
        perm, _ = jax.lax.scan(swap, perm, jnp.arange(piv.shape[-1]))
        P = jnp.eye(m, dtype=lu_v.dtype)[perm].T
        return P, L, U

    return op(fn, lu_data, lu_pivots, op_name="lu_unpack")


def matrix_exp(x, name=None):
    return op(jax.scipy.linalg.expm, x, op_name="matrix_exp")


def householder_product(x, tau, name=None):
    """Q from Householder reflectors (reference householder_product /
    LAPACK orgqr)."""
    def fn(a, t):
        m, n = a.shape[-2], a.shape[-1]
        Q = jnp.eye(m, dtype=a.dtype)
        def body(i, Q):
            v = jnp.where(jnp.arange(m) < i, 0.0,
                          jnp.where(jnp.arange(m) == i, 1.0, a[:, i]))
            H = jnp.eye(m, dtype=a.dtype) - t[i] * jnp.outer(v, v)
            return Q @ H
        Q = jax.lax.fori_loop(0, t.shape[-1], body, Q)
        return Q[:, :n]

    return op(fn, x, tau, op_name="householder_product")


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    def fn(v):
        return jnp.linalg.norm(v.reshape(-1) if axis is None else v,
                               ord=p, axis=None if axis is None else axis,
                               keepdims=keepdim if axis is not None else False)

    return op(fn, x, op_name="vector_norm")


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    def fn(v):
        return jnp.linalg.norm(v, ord=p, axis=tuple(axis), keepdims=keepdim)

    return op(fn, x, op_name="matrix_norm")


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (reference svd_lowrank; Halko et al.)."""
    import numpy as _np

    def fn(a, *rest):
        if rest:
            a = a - rest[0]
        m, n = a.shape[-2], a.shape[-1]
        rs = _np.random.RandomState(0)
        omega = jnp.asarray(rs.randn(n, q).astype(_np.float32))
        Y = a @ omega
        for _ in range(niter):
            Y = a @ (a.T @ Y)
        Q, _ = jnp.linalg.qr(Y)
        B = Q.T @ a
        u_b, s, vt = jnp.linalg.svd(B, full_matrices=False)
        return Q @ u_b, s, vt.T

    args = [x] + ([M] if M is not None else [])
    return op(fn, *args, op_name="svd_lowrank")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def mean_removed(v):
        return v - jnp.mean(v, axis=0, keepdims=True) if center else v

    from ..framework.autograd import call_op as _op

    k = q or min(6, *[int(s) for s in x.shape[-2:]])
    centered = _op(mean_removed, x, op_name="pca_center")
    return svd_lowrank(centered, q=k, niter=niter)

"""Linear algebra ops (reference: python/paddle/tensor/linalg.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._helpers import Tensor, op, val
from .math import bmm, dot, matmul, mm, mv  # noqa: F401 - re-exported


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def fn(v):
        if axis is None:
            flat = v.reshape(-1)
            if p in ("fro", 2):
                return jnp.sqrt(jnp.sum(flat * flat))
            if p == 1:
                return jnp.sum(jnp.abs(flat))
            if p == np.inf or p == float("inf"):
                return jnp.max(jnp.abs(flat))
            if p == -np.inf or p == float("-inf"):
                return jnp.min(jnp.abs(flat))
            return jnp.sum(jnp.abs(flat) ** p) ** (1.0 / p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro":
            return jnp.sqrt(jnp.sum(v * v, axis=ax, keepdims=keepdim))
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(v) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return op(fn, x, op_name="norm")


def dist(x, y, p=2, name=None):
    def fn(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype))
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)

    return op(fn, x, y, op_name="dist")


def cond(x, p=None, name=None):
    return op(lambda v: jnp.linalg.cond(v, p=p), x)


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else _first_dim3(x)
    return op(lambda a, b: jnp.cross(a, b, axis=ax), x, y, op_name="cross")


def _first_dim3(x):
    for i, s in enumerate(x.shape):
        if s == 3:
            return i
    return -1


def cholesky(x, upper=False, name=None):
    def fn(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return op(fn, x, op_name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)

    return op(fn, x, y)


def qr(x, mode="reduced", name=None):
    outs = op(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), x, op_name="qr")
    return outs


def svd(x, full_matrices=False, name=None):
    outs = op(lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)), x, op_name="svd")
    return outs


def eig(x, name=None):
    w, v = np.linalg.eig(x.numpy())
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    outs = op(lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), x, op_name="eigh")
    return outs


def eigvals(x, name=None):
    return Tensor(np.linalg.eigvals(x.numpy()))


def eigvalsh(x, UPLO="L", name=None):
    return op(lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), x)


def inv(x, name=None):
    return op(jnp.linalg.inv, x, op_name="inv")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return op(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), x)


def solve(x, y, name=None):
    return op(jnp.linalg.solve, x, y, op_name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return op(fn, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = np.linalg.lstsq(x.numpy(), y.numpy(), rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(np.asarray(rank)), Tensor(sv)


def lu(x, pivot=True, get_infos=False, name=None):
    lu_mat, piv = jax.scipy.linalg.lu_factor(x._value)
    outs = [Tensor(lu_mat, _internal=True), Tensor((piv + 1).astype("int32"), _internal=True)]
    if get_infos:
        outs.append(Tensor(jnp.zeros((), "int32"), _internal=True))
    return tuple(outs)


def matrix_power(x, n, name=None):
    return op(lambda v: jnp.linalg.matrix_power(v, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return op(lambda v: jnp.linalg.matrix_rank(v, rtol=tol).astype("int64"), x)


def det(x, name=None):
    return op(jnp.linalg.det, x, op_name="det")


def slogdet(x, name=None):
    def fn(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])

    return op(fn, x)


def multi_dot(x, name=None):
    return op(lambda *vs: jnp.linalg.multi_dot(vs), *x)


def histogram(input, bins=100, min=0, max=0, name=None):
    arr = input.numpy()
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    hist, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return Tensor(hist.astype(np.int64))


def bincount(x, weights=None, minlength=0, name=None):
    if weights is not None:
        return op(
            lambda v, w: jnp.bincount(v, weights=w, minlength=minlength,
                                      length=int(np.maximum(x.numpy().max(initial=0) + 1, minlength))),
            x,
            weights,
        )
    n = int(np.maximum(x.numpy().max(initial=0) + 1, minlength))
    return op(lambda v: jnp.bincount(v, minlength=minlength, length=n), x)


def corrcoef(x, rowvar=True, name=None):
    return op(lambda v: jnp.corrcoef(v, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return op(lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0), x)

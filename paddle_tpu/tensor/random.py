"""Random sampling ops (reference: python/paddle/tensor/random.py).

All sampling flows through ``framework.random.next_key()`` so that eager code
uses the global seeded stream while jit-traced code gets fold_in'd traced keys.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.random import next_key
from ._helpers import Tensor, op, val


def _dt(dtype):
    return dtype_mod.convert_dtype(dtype) if dtype is not None else dtype_mod.get_default_dtype()


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(val(s)) for s in shape)


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, min=0.0, max=1.0)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def standard_normal(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(next_key(), _shape(shape), _dt(dtype)), _internal=True)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            np.shape(m) if not hasattr(m, "shape") else m.shape,
            np.shape(s) if not hasattr(s, "shape") else s.shape,
        )
        return Tensor(
            jax.random.normal(next_key(), shp, dtype_mod.get_default_dtype()) * s + m,
            _internal=True,
        )
    shp = _shape(shape) if shape is not None else ()
    return Tensor(
        jax.random.normal(next_key(), shp, dtype_mod.get_default_dtype()) * std + mean,
        _internal=True,
    )


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(
        jax.random.uniform(key, _shape(shape), _dt(dtype), minval=val(min), maxval=val(max)),
        _internal=True,
    )


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x.set_value(uniform(x.shape, x.dtype, min, max, seed))
    return x


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(
        jax.random.randint(next_key(), _shape(shape), int(low), int(high)).astype(_dt(dtype)),
        _internal=True,
    )


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_key(), int(n)).astype(_dt(dtype)), _internal=True)


def rand_like(x, dtype=None, name=None):
    return rand(x.shape, dtype or x.dtype)


def randn_like(x, dtype=None, name=None):
    return randn(x.shape, dtype or x.dtype)


def bernoulli(x, name=None):
    k = next_key()
    return op(lambda v: jax.random.bernoulli(k, v).astype(v.dtype), x, op_name="bernoulli")


def poisson(x, name=None):
    k = next_key()
    return op(lambda v: jax.random.poisson(k, v).astype(v.dtype), x)


def multinomial(x, num_samples=1, replacement=False, name=None):
    k = next_key()

    def fn(v):
        logits = jnp.log(jnp.maximum(v, 1e-30))
        if replacement:
            return jax.random.categorical(k, logits, axis=-1, shape=v.shape[:-1] + (num_samples,)).astype("int64")
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(k, v.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx.astype("int64")

    return op(fn, x, op_name="multinomial")


def exponential_(x, lam=1.0, name=None):
    k = next_key()
    x._value = jax.random.exponential(k, x._value.shape, x._value.dtype) / lam
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x.set_value(normal(mean, std, x.shape))
    return x

"""Tensor attribute ops (reference: python/paddle/tensor/attribute.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ._helpers import Tensor


def shape(x):
    """paddle.shape returns a 1-D int32 tensor of the runtime shape."""
    return Tensor(np.asarray(x.shape, dtype=np.int32))


def rank(x):
    return Tensor(np.asarray(x.ndim, dtype=np.int32))


def is_floating_point(x):
    return dtype_mod.is_floating_point(x.dtype)


def is_integer(x):
    return dtype_mod.is_integer(x.dtype)


def is_complex(x):
    return dtype_mod.is_complex(x.dtype)

"""Shape / layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

builtins_slice = builtins.slice
builtins_sum = builtins.sum

from ._helpers import Tensor, normalize_axis, op, val


def reshape(x, shape, name=None):
    shape = tuple(int(val(s)) for s in shape) if not isinstance(shape, Tensor) else tuple(
        int(s) for s in shape.numpy()
    )
    # paddle semantics: 0 copies the corresponding input dim (fluid reshape_op)
    if 0 in shape:
        shape = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return op(lambda v: jnp.reshape(v, shape), x, op_name="reshape")


def reshape_(x, shape, name=None):
    x._replace_from(reshape(x, shape))
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    s = start_axis + nd if start_axis < 0 else start_axis
    e = stop_axis + nd if stop_axis < 0 else stop_axis

    def fn(v):
        new_shape = v.shape[:s] + (-1,) + v.shape[e + 1 :]
        return jnp.reshape(v, new_shape)

    return op(fn, x, op_name="flatten")


def transpose(x, perm, name=None):
    perm = [int(p) for p in perm]
    return op(lambda v: jnp.transpose(v, perm), x, op_name="transpose")


def t(x, name=None):
    if x.ndim < 2:
        return x.clone()
    return op(lambda v: jnp.swapaxes(v, -1, -2), x, op_name="t")


def moveaxis(x, source, destination, name=None):
    return op(lambda v: jnp.moveaxis(v, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return op(lambda v: jnp.swapaxes(v, axis0, axis1), x)


def squeeze(x, axis=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(a + v.ndim if a < 0 else a for a in axes)
        axes = tuple(a for a in axes if v.shape[a] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v

    return op(fn, x, op_name="squeeze")


def squeeze_(x, axis=None, name=None):
    x._replace_from(squeeze(x, axis))
    return x


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(val(a)) for a in axes]

    def fn(v):
        out = v
        for a in sorted(a + out.ndim + 1 if a < 0 else a for a in axes):
            out = jnp.expand_dims(out, a)
        return out

    return op(fn, x, op_name="unsqueeze")


def unsqueeze_(x, axis, name=None):
    x._replace_from(unsqueeze(x, axis))
    return x


def concat(x, axis=0, name=None):
    tensors = [t if isinstance(t, Tensor) else Tensor(t) for t in x]
    ax = int(val(axis))
    return op(lambda *vs: jnp.concatenate(vs, axis=ax), *tensors, op_name="concat")


def stack(x, axis=0, name=None):
    tensors = [t if isinstance(t, Tensor) else Tensor(t) for t in x]
    return op(lambda *vs: jnp.stack(vs, axis=axis), *tensors, op_name="stack")


def unstack(x, axis=0, num=None, name=None):
    n = num or x.shape[axis]
    outs = op(
        lambda v: tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(v, n, axis=axis)),
        x,
        op_name="unstack",
    )
    return list(outs)


def unbind(input, axis=0, name=None):
    return unstack(input, axis=axis)


def split(x, num_or_sections, axis=0, name=None):
    ax = int(val(axis))
    if isinstance(num_or_sections, int):
        n = num_or_sections
        outs = op(lambda v: tuple(jnp.split(v, n, axis=ax)), x, op_name="split")
    else:
        secs = [int(val(s)) for s in num_or_sections]
        total = x.shape[ax]
        known = builtins_sum(s for s in secs if s >= 0)
        secs = [s if s >= 0 else total - known for s in secs]
        idx = np.cumsum(secs)[:-1].tolist()
        outs = op(lambda v: tuple(jnp.split(v, idx, axis=ax)), x, op_name="split")
    return list(outs)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tile(x, repeat_times, name=None):
    reps = tuple(int(val(r)) for r in repeat_times)
    return op(lambda v: jnp.tile(v, reps), x, op_name="tile")


def expand(x, shape, name=None):
    shape = tuple(int(val(s)) for s in shape)

    def fn(v):
        tgt = list(shape)
        off = len(tgt) - v.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = v.shape[i - off] if i >= off else 1
        return jnp.broadcast_to(v, tuple(tgt))

    return op(fn, x, op_name="expand")


def expand_as(x, y, name=None):
    tgt = tuple(y.shape)
    return op(lambda v: jnp.broadcast_to(v, tgt), x, op_name="expand_as")


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    outs = op(lambda *vs: tuple(jnp.broadcast_arrays(*vs)), *inputs)
    return list(outs)


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return op(lambda v: jnp.flip(v, axis=tuple(axes)), x, op_name="flip")


def roll(x, shifts, axis=None, name=None):
    return op(lambda v: jnp.roll(v, shifts, axis=axis), x, op_name="roll")


def rot90(x, k=1, axes=(0, 1), name=None):
    return op(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), x)


def gather(x, index, axis=0, name=None):
    ax = int(val(axis))
    return op(lambda v, i: jnp.take(v, i.reshape(-1) if i.ndim > 1 else i, axis=ax), x, index,
              op_name="gather")


def gather_nd(x, index, name=None):
    def fn(v, idx):
        # index [..., k] gathers v[idx[...,0], ..., idx[...,k-1]]
        k = idx.shape[-1]
        idx_tuple = tuple(idx[..., j] for j in range(k))
        return v[idx_tuple]

    return op(fn, x, index, op_name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        base = v.at[i].set(jnp.zeros_like(u))
        return base.at[i].add(u)

    return op(fn, x, index, updates, op_name="scatter")


def scatter_(x, index, updates, overwrite=True, name=None):
    x._replace_from(scatter(x, index, updates, overwrite))
    return x


def scatter_nd_add(x, index, updates, name=None):
    def fn(v, idx, u):
        k = idx.shape[-1]
        idx_tuple = tuple(idx[..., j] for j in range(k))
        return v.at[idx_tuple].add(u)

    return op(fn, x, index, updates, op_name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    z = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(z, index, updates)


def index_select(x, index, axis=0, name=None):
    return op(lambda v, i: jnp.take(v, i, axis=axis), x, index, op_name="index_select")


def index_sample(x, index, name=None):
    return op(lambda v, i: jnp.take_along_axis(v, i, axis=1), x, index, op_name="index_sample")


def take_along_axis(arr, indices, axis, name=None):
    return op(lambda v, i: jnp.take_along_axis(v, i, axis=axis), arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def fn(v, i, u):
        u = jnp.broadcast_to(u, i.shape).astype(v.dtype)
        dims = [jnp.arange(s).reshape([-1 if d == j else 1 for d in range(v.ndim)])
                for j, s in enumerate(v.shape)]
        idx = list(jnp.broadcast_arrays(*[dims[j] for j in range(v.ndim)]))
        # replace the target axis index with `i` broadcast to full shape
        full_idx = []
        for j in range(v.ndim):
            if j == axis:
                full_idx.append(i)
            else:
                shape = [1] * v.ndim
                shape[j] = v.shape[j]
                base = jnp.arange(v.shape[j]).reshape(shape)
                full_idx.append(jnp.broadcast_to(base, i.shape))
        if reduce == "assign":
            return v.at[tuple(full_idx)].set(u)
        if reduce == "add":
            return v.at[tuple(full_idx)].add(u)
        if reduce == "multiply" or reduce == "mul":
            return v.at[tuple(full_idx)].multiply(u)
        raise ValueError(f"unknown reduce {reduce}")

    return op(fn, arr, indices, values, op_name="put_along_axis")


def masked_select(x, mask, name=None):
    # dynamic shapes don't compile on TPU; eager-only (numpy fallback)
    vals = x.numpy()[np.asarray(mask.numpy(), dtype=np.bool_)]
    return Tensor(vals)


def masked_fill(x, mask, value, name=None):
    v = val(value)
    return op(lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a), x, mask)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        return op(
            lambda v, r: jnp.repeat(v, r, axis=axis, total_repeat_length=int(repeats.numpy().sum())),
            x,
            repeats,
        )
    return op(lambda v: jnp.repeat(v, repeats, axis=axis), x, op_name="repeat_interleave")


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None,
           dtype="int64", name=None):
    # dynamic output shape: host-side eager op
    res = np.unique(
        x.numpy(), return_index=return_index, return_inverse=return_inverse,
        return_counts=return_counts, axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor(res)
    outs = [Tensor(r.astype(np.int64) if i > 0 else r) for i, r in enumerate(res)]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64",
                       name=None):
    arr = x.numpy()
    if axis is None:
        arr = arr.reshape(-1)
    keep = np.ones(arr.shape[0], dtype=np.bool_)
    keep[1:] = np.any(
        arr[1:].reshape(arr.shape[0] - 1, -1) != arr[:-1].reshape(arr.shape[0] - 1, -1), axis=1
    )
    out = Tensor(arr[keep])
    if not (return_inverse or return_counts):
        return out
    outs = [out]
    idx = np.cumsum(keep) - 1
    if return_inverse:
        outs.append(Tensor(idx.astype(np.int64)))
    if return_counts:
        outs.append(Tensor(np.bincount(idx).astype(np.int64)))
    return tuple(outs)


def slice(input, axes, starts, ends, name=None):
    def fn(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e in zip(axes, starts, ends):
            idx[a] = builtins_slice(int(val(s)), int(val(e)))
        return v[tuple(idx)]

    return op(fn, input, op_name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    def fn(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = builtins_slice(int(val(s)), int(val(e)), int(val(st)))
        return v[tuple(idx)]

    return op(fn, x, op_name="strided_slice")


def as_real(x, name=None):
    return op(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), x)


def as_complex(x, name=None):
    return op(lambda v: v[..., 0] + 1j * v[..., 1], x)


def tensordot(x, y, axes=2, name=None):
    return op(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def atleast_1d(*inputs, name=None):
    outs = [op(jnp.atleast_1d, t) for t in inputs]
    return outs if len(outs) > 1 else outs[0]


def atleast_2d(*inputs, name=None):
    outs = [op(jnp.atleast_2d, t) for t in inputs]
    return outs if len(outs) > 1 else outs[0]


def atleast_3d(*inputs, name=None):
    outs = [op(jnp.atleast_3d, t) for t in inputs]
    return outs if len(outs) > 1 else outs[0]


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return op(lambda v: v.view(shape_or_dtype), x)


def _idx_dtype():
    """int64 per paddle API, narrowed like convert_dtype when x64 is off —
    avoids jax's truncation warning on every index-producing op."""
    from ..framework import dtype as dtype_mod

    return dtype_mod.convert_dtype("int64")


def cast(x, dtype):
    return x.astype(dtype)


def unflatten(x, axis, shape, name=None):
    """Split one dim into `shape` (reference: paddle.unflatten)."""
    def fn(v):
        ax = axis % v.ndim
        new = list(v.shape[:ax]) + [int(s) for s in shape] + \
            list(v.shape[ax + 1:])
        if -1 in shape:
            i = new.index(-1)
            known = 1
            for s in shape:
                if s != -1:
                    known *= int(s)
            new[i] = v.shape[ax] // known
        return v.reshape(new)

    return op(fn, x, op_name="unflatten")


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view materialized as a gather (reference: paddle.as_strided —
    a raw-memory view there; XLA has no aliasing views, so this builds the
    equivalent tensor)."""
    import numpy as _np

    def fn(v):
        flat = v.reshape(-1)
        idx = _np.full(tuple(shape), offset, _np.int64)
        for d, (s, st) in enumerate(zip(shape, stride)):
            r = _np.arange(s) * st
            expand = [1] * len(shape)
            expand[d] = s
            idx = idx + r.reshape(expand)
        return flat[jnp.asarray(idx)]

    return op(fn, x, op_name="as_strided")


# -------------------- split/stack family tail (reference manipulation API)

def tensor_split(x, num_or_indices, axis=0, name=None):
    def fn(v):
        if isinstance(num_or_indices, int):
            return tuple(jnp.array_split(v, num_or_indices, axis=axis))
        return tuple(jnp.split(v, list(num_or_indices), axis=axis))

    return op(fn, x, op_name="tensor_split")


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def hstack(x, name=None):
    return op(lambda *vs: jnp.hstack(vs), *x, op_name="hstack")


def vstack(x, name=None):
    return op(lambda *vs: jnp.vstack(vs), *x, op_name="vstack")


def dstack(x, name=None):
    return op(lambda *vs: jnp.dstack(vs), *x, op_name="dstack")


def column_stack(x, name=None):
    return op(lambda *vs: jnp.column_stack(vs), *x, op_name="column_stack")


def row_stack(x, name=None):
    return vstack(x)


def crop(x, shape=None, offsets=None, name=None):
    """Static crop (reference crop_tensor_op)."""
    import builtins

    offs = [int(o) for o in (offsets or [])]

    def fn(v):
        o2 = offs if offs else [0] * v.ndim
        shp = [int(s) if int(s) != -1 else v.shape[i] - o2[i]
               for i, s in enumerate(shape)]
        sl = tuple(builtins.slice(o, o + s) for o, s in zip(o2, shp))
        return v[sl]

    return op(fn, x, op_name="crop")


def index_add(x, index, axis, value, name=None):
    def fn(v, idx, val):
        moved = jnp.moveaxis(v, axis, 0)
        vmoved = jnp.moveaxis(val, axis, 0)
        out = moved.at[idx].add(vmoved)
        return jnp.moveaxis(out, 0, axis)

    return op(fn, x, index, value, op_name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    def fn(v, val, *idx):
        if accumulate:
            return v.at[tuple(idx)].add(val)
        return v.at[tuple(idx)].set(val)

    return op(fn, x, value, *indices, op_name="index_put")


def masked_scatter(x, mask, value, name=None):
    """Fill True positions of mask with consecutive elements of value
    (reference masked_scatter). Mask must be eager (data-dependent count)."""
    import numpy as _np

    mval = mask._value if hasattr(mask, "_value") else mask
    if isinstance(mval, jax.core.Tracer):
        raise ValueError("masked_scatter needs a concrete mask (host op)")
    m = _np.asarray(mval).astype(bool)
    flat_idx = _np.nonzero(m.reshape(-1))[0]

    def fn(v, val):
        flat = v.reshape(-1)
        src = val.reshape(-1)[: flat_idx.size]
        return flat.at[jnp.asarray(flat_idx)].set(src).reshape(v.shape)

    return op(fn, x, value, op_name="masked_scatter")


def reverse(x, axis, name=None):
    """Reference spelling for flip (paddle.reverse, reverse_op.cc)."""
    return flip(x, axis, name=name)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    """Extract a diagonal view (reference: diagonal_op.cc)."""
    return op(lambda v: jnp.diagonal(v, offset=int(offset), axis1=int(axis1),
                                     axis2=int(axis2)),
              x, op_name="diagonal")


def multiplex(inputs, index, name=None):
    """Row-wise select across candidate tensors: out[i] = inputs[index[i]][i]
    (reference: multiplex_op.cc)."""
    seq = list(inputs)
    if len(seq) < 2:
        raise ValueError("multiplex expects at least two candidate tensors")

    def fn(idx, *cands):
        stacked = jnp.stack(cands, axis=0)          # [n, d0, ...]
        rows = jnp.arange(stacked.shape[1])
        sel = jnp.asarray(idx).reshape(-1).astype(jnp.int32)
        return stacked[sel, rows]

    return op(fn, index, *seq, op_name="multiplex")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """Recompute class indices for one shard of a vocab-sharded label space
    (reference: shard_index_op.cc, used by TP cross-entropy): indices inside
    [shard_id*shard_size, (shard_id+1)*shard_size) map to the local offset,
    everything else becomes ignore_value."""
    if shard_id < 0 or shard_id >= nshards:
        raise ValueError(
            f"shard_id {shard_id} out of range for nshards {nshards}")
    shard_size = (int(index_num) + int(nshards) - 1) // int(nshards)
    lo = int(shard_id) * shard_size

    def fn(v):
        local = v - lo
        ok = (v >= lo) & (v < lo + shard_size)
        return jnp.where(ok, local, jnp.asarray(ignore_value, v.dtype))

    return op(fn, input, op_name="shard_index")


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """Set the (offset) diagonal to `value` (reference: fill_diagonal_op).

    wrap=True on a tall 2-D matrix restarts the diagonal after every
    `cols` rows (the reference/torch tall-matrix semantics)."""
    off = int(offset)

    def fn(v):
        R, C = v.shape[-2], v.shape[-1]
        if wrap and v.ndim == 2 and R > C and off == 0:
            flat = v.reshape(-1)
            pos = jnp.arange(0, R * C, C + 1)
            return flat.at[pos].set(value).reshape(R, C)
        # diagonal length honoring rectangular shapes + offset
        n = min(R - max(-off, 0), C - max(off, 0))
        if n <= 0:
            return v
        r = jnp.arange(n)
        rows = r + max(-off, 0)
        cols = r + max(off, 0)
        return v.at[..., rows, cols].set(value)

    return op(fn, x, op_name="fill_diagonal")


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    x._replace_from(fill_diagonal(x, value, offset=offset, wrap=wrap))
    return x


def shuffle_batch(x, seed=None, name=None):
    """Random row permutation along dim0 (reference: shuffle_batch_op);
    returns (shuffled, order) like the reference's (out, shuffle_idx)."""
    from ..framework import random as rng_mod
    import jax

    key = jax.random.key(int(seed)) if seed not in (None, 0) else \
        rng_mod.next_key()

    def fn(v):
        order = jax.random.permutation(key, v.shape[0])
        return v[order], order.astype(_idx_dtype())

    return op(fn, x, op_name="shuffle_batch")


def partial_concat(inputs, start_index=0, length=-1, name=None):
    """Concat a column slice of each input (reference: partial_concat_op):
    out = concat([x[:, start:start+length] for x in inputs], axis=1)."""
    seq = list(inputs)

    def fn(*vals):
        cols = []
        for v in vals:
            end = v.shape[1] if length == -1 else start_index + length
            cols.append(v[:, start_index:end])
        return jnp.concatenate(cols, axis=1)

    return op(fn, *seq, op_name="partial_concat")


def partial_sum(inputs, start_index=0, length=-1, name=None):
    """Sum a column slice of each input (reference: partial_sum_op)."""
    seq = list(inputs)

    def fn(*vals):
        out = None
        for v in vals:
            end = v.shape[1] if length == -1 else start_index + length
            piece = v[:, start_index:end]
            out = piece if out is None else out + piece
        return out

    return op(fn, *seq, op_name="partial_sum")


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y up to x's shape with pad_value (reference:
    pad_constant_like_op)."""
    def fn(xv, yv):
        pads = [(0, xs - ys) for xs, ys in zip(xv.shape, yv.shape)]
        return jnp.pad(yv, pads, constant_values=pad_value)

    return op(fn, x, y, op_name="pad_constant_like")

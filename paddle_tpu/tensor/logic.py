"""Comparison / logical / bitwise ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ._helpers import Tensor, as_tensor, op, val


def _binary(fn, x, y, name=""):
    if not isinstance(x, Tensor):
        x = as_tensor(x, y if isinstance(y, Tensor) else None)
    y = as_tensor(y, x)
    return op(fn, x, y, op_name=name)


def equal(x, y, name=None):
    return _binary(jnp.equal, x, y, "equal")


def not_equal(x, y, name=None):
    return _binary(jnp.not_equal, x, y, "not_equal")


def greater_than(x, y, name=None):
    return _binary(jnp.greater, x, y, "greater_than")


def greater_equal(x, y, name=None):
    return _binary(jnp.greater_equal, x, y, "greater_equal")


def less_than(x, y, name=None):
    return _binary(jnp.less, x, y, "less_than")


def less_equal(x, y, name=None):
    return _binary(jnp.less_equal, x, y, "less_equal")


def logical_and(x, y, out=None, name=None):
    return _binary(jnp.logical_and, x, y, "logical_and")


def logical_or(x, y, out=None, name=None):
    return _binary(jnp.logical_or, x, y, "logical_or")


def logical_xor(x, y, out=None, name=None):
    return _binary(jnp.logical_xor, x, y, "logical_xor")


def logical_not(x, out=None, name=None):
    return op(jnp.logical_not, x, op_name="logical_not")


def bitwise_and(x, y, out=None, name=None):
    return _binary(jnp.bitwise_and, x, y)


def bitwise_or(x, y, out=None, name=None):
    return _binary(jnp.bitwise_or, x, y)


def bitwise_xor(x, y, out=None, name=None):
    return _binary(jnp.bitwise_xor, x, y)


def bitwise_not(x, out=None, name=None):
    return op(jnp.bitwise_not, x)


def equal_all(x, y, name=None):
    return op(lambda a, b: jnp.asarray(jnp.array_equal(a, b)), x, y, op_name="equal_all")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return op(
        lambda a, b: jnp.asarray(jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)),
        x,
        y,
        op_name="allclose",
    )


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return op(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        x,
        y,
        op_name="isclose",
    )


def is_empty(x, name=None):
    return Tensor(np.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)

"""Creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.tensor import Tensor, to_tensor  # noqa: F401
from ._helpers import op, val, convert_dtype


def _dt(dtype):
    return convert_dtype(dtype) if dtype is not None else dtype_mod.get_default_dtype()


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)), _internal=True)


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)), _internal=True)


def full(shape, fill_value, dtype=None, name=None):
    fill_value = val(fill_value)
    if dtype is None and isinstance(fill_value, (bool, int)):
        dtype = "int64" if isinstance(fill_value, int) and not isinstance(fill_value, bool) else "bool"
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)), _internal=True)


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return op(lambda v: jnp.zeros_like(v, dtype=convert_dtype(dtype) if dtype else None), x)


def ones_like(x, dtype=None, name=None):
    return op(lambda v: jnp.ones_like(v, dtype=convert_dtype(dtype) if dtype else None), x)


def full_like(x, fill_value, dtype=None, name=None):
    return op(
        lambda v: jnp.full_like(v, val(fill_value), dtype=convert_dtype(dtype) if dtype else None),
        x,
    )


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (
            "int64"
            if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
            else dtype_mod.get_default_dtype()
        )
    return Tensor(jnp.arange(start, end, step, dtype=convert_dtype(dtype)), _internal=True)


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(val(start), val(stop), int(val(num)), dtype=_dt(dtype)), _internal=True)


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(
        jnp.logspace(val(start), val(stop), int(val(num)), base=base, dtype=_dt(dtype)),
        _internal=True,
    )


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)), _internal=True)


def diag(x, offset=0, padding_value=0, name=None):
    def fn(v):
        if v.ndim == 1 and padding_value != 0:
            d = jnp.diag(v, k=offset)
            mask = jnp.eye(*d.shape, k=offset, dtype=bool)
            return jnp.where(mask, d, padding_value)
        return jnp.diag(v, k=offset)

    return op(fn, x, op_name="diag")


def diagflat(x, offset=0, name=None):
    return op(lambda v: jnp.diagflat(v, k=offset), x)


def tril(x, diagonal=0, name=None):
    return op(lambda v: jnp.tril(v, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return op(lambda v: jnp.triu(v, k=diagonal), x)


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = op(lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")), *args, op_name="meshgrid")
    return list(outs)


def assign(x, output=None):
    src = Tensor(np.asarray(x)) if not isinstance(x, Tensor) else x
    res = op(lambda v: v + 0 if jnp.issubdtype(v.dtype, jnp.number) else v, src, op_name="assign")
    if output is not None:
        output._replace_from(res)
        return output
    return res


def clone(x, name=None):
    return x.clone()


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype="int64"), _internal=True)


def complex(real, imag, name=None):
    return op(lambda r, i: r + 1j * i, real, imag, op_name="complex")


def real(x, name=None):
    return op(jnp.real, x)


def imag(x, name=None):
    return op(jnp.imag, x)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(val(s)) if not isinstance(s, (int, np.integer)) else int(s) for s in shape)

"""Shared dispatch helpers for the functional kernel library.

Every public op is a thin wrapper calling ``op(fn, *tensor_args, **static_kw)``
where ``fn`` is a pure jax function — the pten-style functional kernel
(reference: paddle/pten/kernels/, kernel_registry.h:219). XLA does the fusion;
pallas kernels slot in as alternate ``fn`` bodies where needed.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.autograd import call_op as op  # noqa: F401
from ..framework.tensor import Tensor  # noqa: F401
from ..framework import dtype as dtype_mod


def val(x):
    return x._value if isinstance(x, Tensor) else x


def as_tensor(x, ref: Tensor | None = None):
    """Coerce python scalars / numpy to Tensor, matching ref dtype for scalars."""
    if isinstance(x, Tensor):
        return x
    if ref is not None and isinstance(x, (int, float, bool)):
        return Tensor(jnp.asarray(x, dtype=ref.dtype), _internal=True)
    return Tensor(x)


def normalize_axis(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(a + ndim if a < 0 else a for a in axis)
    a = int(axis)
    return a + ndim if a < 0 else a


def convert_dtype(d):
    return dtype_mod.convert_dtype(d)

"""Shared dispatch helpers for the functional kernel library.

Every public op is a thin wrapper calling ``op(fn, *tensor_args, **static_kw)``
where ``fn`` is a pure jax function — the pten-style functional kernel
(reference: paddle/pten/kernels/, kernel_registry.h:219). XLA does the fusion;
pallas kernels slot in as alternate ``fn`` bodies where needed.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..framework.autograd import call_op as op  # noqa: F401
from ..framework.tensor import Tensor  # noqa: F401
from ..framework import dtype as dtype_mod


def val(x):
    return x._value if isinstance(x, Tensor) else x


# python-scalar → device-array cache: `x * 1.0001 + 0.1` style eager chains
# re-convert the same literals every op, and jnp.asarray + the weak-type
# convert_element_type bind dominate the cached-dispatch latency (profiled
# ~40% of the eager us/op; SURVEY §7 hard part 1). Arrays are immutable, so
# sharing one per (type, value, dtype) is sound. Dtype semantics are exactly
# the uncached paths': an explicit ref dtype, else floats take the (current)
# default dtype as a STRONG type — a weak-typed scalar would change jax
# promotion (e.g. f32-weak + bf16 → bf16) and silently shift numerics.
_scalar_cache: dict = {}


# Cached arrays must not escape into traces: jax lifts closure constants
# into compiled executables by identity, and a shared array reappearing
# across separately-compiled programs corrupts their buffer plans (observed
# as 'supplied N buffers but compiled program expected M' on executor
# replays). Trace-time conversion cost compiles away anyway. The trace
# probe is resolved ONCE at import — this sits on the per-op hot path.
try:
    from jax._src.core import EvalTrace as _EvalTrace, trace_ctx as _trace_ctx

    def _tracing() -> bool:
        return type(_trace_ctx.trace) is not _EvalTrace
except Exception:  # pragma: no cover - jax internals moved
    import warnings as _warnings

    _warnings.warn("paddle_tpu: jax trace-state probe unavailable "
                   "(jax internals changed); eager scalar caching is "
                   "disabled — dispatch will be slower")

    def _tracing() -> bool:
        return True


def _scalar_array(x, dtype):
    if dtype is None and isinstance(x, float):
        dtype = dtype_mod.get_default_dtype()
    if _tracing():
        return jnp.asarray(np.asarray(x, dtype=dtype))
    # -0.0 == 0.0 hashes equal, so a plain value key would hand a cached
    # +0.0 array to a -0.0 request (flipping 1/x, copysign, atan2); carry
    # the sign of zero explicitly for floats
    if isinstance(x, float):
        key = (type(x), x, math.copysign(1.0, x), dtype)
    else:
        key = (type(x), x, dtype)
    arr = _scalar_cache.get(key)
    if arr is None:
        if len(_scalar_cache) > 4096:
            _scalar_cache.clear()
        arr = _scalar_cache[key] = jnp.asarray(np.asarray(x, dtype=dtype))
    return arr


def as_tensor(x, ref: Tensor | None = None):
    """Coerce python scalars / numpy to Tensor, matching ref dtype for scalars."""
    if isinstance(x, Tensor):
        return x
    if isinstance(x, (int, float, bool)):
        dtype = ref.dtype if ref is not None else None
        return Tensor(_scalar_array(x, dtype), _internal=True)
    return Tensor(x)


def normalize_axis(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(a + ndim if a < 0 else a for a in axis)
    a = int(axis)
    return a + ndim if a < 0 else a


def convert_dtype(d):
    return dtype_mod.convert_dtype(d)

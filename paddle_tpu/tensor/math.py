"""Elementwise + reduction math ops.

Reference: python/paddle/tensor/math.py and the elementwise/reduce op families
(paddle/fluid/operators/elementwise/, reduce_ops/). Each op is a jax function;
XLA fuses chains of these into single kernels, which is the TPU replacement for
the reference's hand-fused CUDA kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._helpers import Tensor, as_tensor, normalize_axis, op, val


def _binary(fn, x, y, name=""):
    if not isinstance(x, Tensor):
        x = as_tensor(x, y if isinstance(y, Tensor) else None)
    y = as_tensor(y, x)
    return op(fn, x, y, op_name=name)


# ----------------------------------------------------------------- elementwise
def add(x, y, name=None):
    return _binary(jnp.add, x, y, "add")


def subtract(x, y, name=None):
    return _binary(jnp.subtract, x, y, "subtract")


def multiply(x, y, name=None):
    return _binary(jnp.multiply, x, y, "multiply")


def divide(x, y, name=None):
    return _binary(jnp.true_divide, x, y, "divide")


def floor_divide(x, y, name=None):
    return _binary(jnp.floor_divide, x, y, "floor_divide")


def remainder(x, y, name=None):
    return _binary(jnp.remainder, x, y, "remainder")


mod = remainder
floor_mod = remainder


def pow(x, y, name=None):
    return _binary(jnp.power, x, y, "pow")


def maximum(x, y, name=None):
    return _binary(jnp.maximum, x, y, "maximum")


def minimum(x, y, name=None):
    return _binary(jnp.minimum, x, y, "minimum")


def fmax(x, y, name=None):
    return _binary(jnp.fmax, x, y, "fmax")


def fmin(x, y, name=None):
    return _binary(jnp.fmin, x, y, "fmin")


def atan2(x, y, name=None):
    return _binary(jnp.arctan2, x, y, "atan2")


def heaviside(x, y, name=None):
    return _binary(jnp.heaviside, x, y, "heaviside")


def inner(x, y, name=None):
    return _binary(jnp.inner, x, y, "inner")


def outer(x, y, name=None):
    return _binary(lambda a, b: jnp.outer(a, b), x, y, "outer")


def logaddexp(x, y, name=None):
    return _binary(jnp.logaddexp, x, y, "logaddexp")


def nextafter(x, y, name=None):
    return _binary(jnp.nextafter, x, y, "nextafter")


def copysign(x, y, name=None):
    return _binary(jnp.copysign, x, y, "copysign")


# ------------------------------------------------------------------- unary
def _unary(fn, x, name=""):
    if not isinstance(x, Tensor):
        x = Tensor(x)
    return op(fn, x, op_name=name)


def abs(x, name=None):
    return _unary(jnp.abs, x, "abs")


def neg(x, name=None):
    return _unary(jnp.negative, x, "neg")


def exp(x, name=None):
    return _unary(jnp.exp, x, "exp")


def expm1(x, name=None):
    return _unary(jnp.expm1, x, "expm1")


def log(x, name=None):
    return _unary(jnp.log, x, "log")


def log2(x, name=None):
    return _unary(jnp.log2, x, "log2")


def log10(x, name=None):
    return _unary(jnp.log10, x, "log10")


def log1p(x, name=None):
    return _unary(jnp.log1p, x, "log1p")


def sqrt(x, name=None):
    return _unary(jnp.sqrt, x, "sqrt")


def rsqrt(x, name=None):
    return _unary(jax.lax.rsqrt, x, "rsqrt")


def square(x, name=None):
    return _unary(jnp.square, x, "square")


def sign(x, name=None):
    return _unary(jnp.sign, x, "sign")


def sin(x, name=None):
    return _unary(jnp.sin, x, "sin")


def cos(x, name=None):
    return _unary(jnp.cos, x, "cos")


def tan(x, name=None):
    return _unary(jnp.tan, x, "tan")


def asin(x, name=None):
    return _unary(jnp.arcsin, x, "asin")


def acos(x, name=None):
    return _unary(jnp.arccos, x, "acos")


def atan(x, name=None):
    return _unary(jnp.arctan, x, "atan")


def sinh(x, name=None):
    return _unary(jnp.sinh, x, "sinh")


def cosh(x, name=None):
    return _unary(jnp.cosh, x, "cosh")


def tanh(x, name=None):
    return _unary(jnp.tanh, x, "tanh")


def asinh(x, name=None):
    return _unary(jnp.arcsinh, x, "asinh")


def acosh(x, name=None):
    return _unary(jnp.arccosh, x, "acosh")


def atanh(x, name=None):
    return _unary(jnp.arctanh, x, "atanh")


def ceil(x, name=None):
    return _unary(jnp.ceil, x, "ceil")


def floor(x, name=None):
    return _unary(jnp.floor, x, "floor")


def round(x, name=None):
    return _unary(jnp.round, x, "round")


def trunc(x, name=None):
    return _unary(jnp.trunc, x, "trunc")


def frac(x, name=None):
    return _unary(lambda v: v - jnp.trunc(v), x, "frac")


def reciprocal(x, name=None):
    return _unary(jnp.reciprocal, x, "reciprocal")


def erf(x, name=None):
    return _unary(jax.scipy.special.erf, x, "erf")


def erfinv(x, name=None):
    return _unary(jax.scipy.special.erfinv, x, "erfinv")


def lgamma(x, name=None):
    return _unary(jax.scipy.special.gammaln, x, "lgamma")


def digamma(x, name=None):
    return _unary(jax.scipy.special.digamma, x, "digamma")


def logit(x, eps=None, name=None):
    def fn(v):
        if eps is not None:
            v = jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(v / (1.0 - v))

    return _unary(fn, x, "logit")


def sigmoid(x, name=None):
    return _unary(jax.nn.sigmoid, x, "sigmoid")


def isfinite(x, name=None):
    return _unary(jnp.isfinite, x, "isfinite")


def isnan(x, name=None):
    return _unary(jnp.isnan, x, "isnan")


def isinf(x, name=None):
    return _unary(jnp.isinf, x, "isinf")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _unary(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = val(scale), val(bias)

    def fn(v):
        out = v * s + b if bias_after_scale else (v + b) * s
        return out

    return _unary(fn, x, "scale")


def increment(x, value=1.0, name=None):
    new = _unary(lambda v: v + value, x, "increment")
    x._replace_from(new)
    return x


def clip(x, min=None, max=None, name=None):
    lo = val(min) if min is not None else None
    hi = val(max) if max is not None else None
    return _unary(lambda v: jnp.clip(v, lo, hi), x, "clip")


def lerp(x, y, weight, name=None):
    w = weight if isinstance(weight, Tensor) else as_tensor(weight, x)
    return op(lambda a, b, t: a + t * (b - a), x, y, w, op_name="lerp")


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _unary(lambda v: scale_b * jnp.tanh(scale_a * v), x, "stanh")


def rad2deg(x, name=None):
    return _unary(jnp.rad2deg, x)


def deg2rad(x, name=None):
    return _unary(jnp.deg2rad, x)


def angle(x, name=None):
    return _unary(jnp.angle, x)


def conj(x, name=None):
    return _unary(jnp.conj, x)


def gcd(x, y, name=None):
    return _binary(jnp.gcd, x, y)


def lcm(x, y, name=None):
    return _binary(jnp.lcm, x, y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return _unary(
        lambda v: jnp.diff(v, n=n, axis=axis, prepend=val(prepend) if prepend is not None else None,
                           append=val(append) if append is not None else None),
        x,
    )


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _unary(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), x)


# ---------------------------------------------------------------- reductions
def _reduce(fn, x, axis, keepdim, name, dtype=None):
    ax = normalize_axis(axis, x.ndim)

    def body(v):
        out = fn(v, axis=ax, keepdims=keepdim)
        if dtype is not None:
            out = out.astype(dtype)
        return out

    return op(body, x, op_name=name)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ._helpers import convert_dtype

    dt = convert_dtype(dtype) if dtype is not None else None
    if dt is None and jnp.issubdtype(x.dtype, jnp.bool_):
        dt = jnp.dtype("int64")
    return _reduce(jnp.sum, x, axis, keepdim, "sum", dtype=dt)


def mean(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.mean, x, axis, keepdim, "mean")


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    from ._helpers import convert_dtype

    dt = convert_dtype(dtype) if dtype is not None else None
    return _reduce(jnp.prod, x, axis, keepdim, "prod", dtype=dt)


def max(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.max, x, axis, keepdim, "max")


def min(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.min, x, axis, keepdim, "min")


def amax(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.max, x, axis, keepdim, "amax")


def amin(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.min, x, axis, keepdim, "amin")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return _reduce(jnp.nansum, x, axis, keepdim, "nansum")


def nanmean(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.nanmean, x, axis, keepdim, "nanmean")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = normalize_axis(axis, x.ndim)
    return op(
        lambda v: jnp.std(v, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        op_name="std",
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = normalize_axis(axis, x.ndim)
    return op(
        lambda v: jnp.var(v, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        op_name="var",
    )


def median(x, axis=None, keepdim=False, name=None):
    ax = normalize_axis(axis, x.ndim)
    return op(lambda v: jnp.median(v, axis=ax, keepdims=keepdim), x, op_name="median")


def quantile(x, q, axis=None, keepdim=False, name=None):
    ax = normalize_axis(axis, x.ndim)
    return op(lambda v: jnp.quantile(v, jnp.asarray(q), axis=ax, keepdims=keepdim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = normalize_axis(axis, x.ndim)
    return op(
        lambda v: jax.scipy.special.logsumexp(v, axis=ax, keepdims=keepdim),
        x,
        op_name="logsumexp",
    )


def all(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.all, x, axis, keepdim, "all")


def any(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.any, x, axis, keepdim, "any")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = normalize_axis(axis, x.ndim)
    return op(lambda v: jnp.count_nonzero(v, axis=ax, keepdims=keepdim).astype("int64"), x)


# ------------------------------------------------------------------- cumulative
def cumsum(x, axis=None, dtype=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.cumsum(v.reshape(-1))
        return jnp.cumsum(v, axis=axis)

    return op(fn, x, op_name="cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    return op(lambda v: jnp.cumprod(v, axis=dim), x, op_name="cumprod")


def cummax(x, axis=None, dtype="int64", name=None):
    def fn(v):
        if axis is None:
            v = v.reshape(-1)
            ax = 0
        else:
            ax = axis
        vals = jax.lax.associative_scan(jnp.maximum, v, axis=ax)
        return vals

    return op(fn, x, op_name="cummax")


def cummin(x, axis=None, dtype="int64", name=None):
    def fn(v):
        if axis is None:
            v = v.reshape(-1)
            ax = 0
        else:
            ax = axis
        return jax.lax.associative_scan(jnp.minimum, v, axis=ax)

    return op(fn, x, op_name="cummin")


# ------------------------------------------------------------------- matmul-ish
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return op(fn, x, y, op_name="matmul")


mm = matmul


def dot(x, y, name=None):
    def fn(a, b):
        if a.ndim == 2:
            return jnp.sum(a * b, axis=-1)
        return jnp.dot(a, b)

    return op(fn, x, y, op_name="dot")


def bmm(x, y, name=None):
    return op(jnp.matmul, x, y, op_name="bmm")


def mv(x, vec, name=None):
    return op(jnp.matmul, x, vec, op_name="mv")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return op(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), input, x, y, op_name="addmm")


def kron(x, y, name=None):
    return op(jnp.kron, x, y, op_name="kron")


def multiply_(x, y):
    x._replace_from(multiply(x, y))
    return x


def add_(x, y):
    x._replace_from(add(x, y))
    return x


def subtract_(x, y):
    x._replace_from(subtract(x, y))
    return x


def divide_(x, y):
    x._replace_from(divide(x, y))
    return x


def scale_(x, scale_v=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x._replace_from(scale(x, scale_v, bias, bias_after_scale))
    return x


def clip_(x, min=None, max=None, name=None):
    x._replace_from(clip(x, min, max))
    return x


# ---------------------------------------------------------------------------
# reference tensor-API tail (math): cdist/take/logcumsumexp/renorm/frexp/
# trapezoid/vander/nanmedian/polygamma/i0
# ---------------------------------------------------------------------------

def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-norm distance (reference: paddle.cdist). p==2 uses the
    matmul expansion — MXU-friendly."""
    def fn(a, b):
        if p == 2.0:
            a2 = jnp.sum(a * a, -1, keepdims=True)
            b2 = jnp.sum(b * b, -1, keepdims=True)
            sq = a2 + jnp.swapaxes(b2, -1, -2) - 2 * (
                a @ jnp.swapaxes(b, -1, -2))
            return jnp.sqrt(jnp.maximum(sq, 0.0))
        diff = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        if p == 0:
            return jnp.sum((diff != 0).astype(a.dtype), -1)
        if jnp.isinf(p):
            return jnp.max(diff, -1)
        return jnp.sum(diff ** p, -1) ** (1.0 / p)

    return op(fn, x, y, op_name="cdist")


def take(x, index, mode="raise", name=None):
    """Flat-index gather (reference: paddle.take); mode wrap/clip supported."""
    def fn(v, idx):
        flat = v.reshape(-1)
        i = idx.astype(jnp.int64) if False else idx
        n = flat.shape[0]
        if mode == "wrap":
            i = ((i % n) + n) % n
        else:  # raise/clip: XLA clamps OOB — 'raise' degrades to clip in-jit
            i = jnp.clip(jnp.where(i < 0, i + n, i), 0, n - 1)
        return flat[i]

    return op(fn, x, index, op_name="take")


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def fn(v):
        a = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else axis
        out = jax.lax.associative_scan(jnp.logaddexp,
                                       a.astype(jnp.float32), axis=ax)
        return out.astype(dtype or v.dtype)

    return op(fn, x, op_name="logcumsumexp")


def renorm(x, p, axis, max_norm, name=None):
    """Per-slice p-norm clamp along `axis` (reference: paddle.renorm)."""
    def fn(v):
        moved = jnp.moveaxis(v, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return op(fn, x, op_name="renorm")


def frexp(x, name=None):
    """(mantissa, exponent) with x = m * 2**e, 0.5<=|m|<1 (paddle.frexp)."""
    def fn(v):
        e = jnp.where(v == 0, 0,
                      jnp.floor(jnp.log2(jnp.abs(
                          jnp.where(v == 0, 1.0, v)))) + 1)
        m = v / jnp.exp2(e)
        return m.astype(v.dtype), e.astype(v.dtype)

    return op(fn, x, op_name="frexp")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def fn(yv, *rest):
        if rest:
            xv = rest[0]
            d = jnp.diff(xv, axis=axis)
        else:
            d = dx if dx is not None else 1.0
        ya = jnp.take(yv, jnp.arange(yv.shape[axis] - 1), axis=axis)
        yb = jnp.take(yv, jnp.arange(1, yv.shape[axis]), axis=axis)
        return jnp.sum((ya + yb) * 0.5 * d, axis=axis)

    args = [y] + ([x] if x is not None else [])
    return op(fn, *args, op_name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def fn(yv, *rest):
        if rest:
            d = jnp.diff(rest[0], axis=axis)
        else:
            d = dx if dx is not None else 1.0
        ya = jnp.take(yv, jnp.arange(yv.shape[axis] - 1), axis=axis)
        yb = jnp.take(yv, jnp.arange(1, yv.shape[axis]), axis=axis)
        return jnp.cumsum((ya + yb) * 0.5 * d, axis=axis)

    args = [y] + ([x] if x is not None else [])
    return op(fn, *args, op_name="cumulative_trapezoid")


def vander(x, n=None, increasing=False, name=None):
    def fn(v):
        cols = n if n is not None else v.shape[0]
        powers = jnp.arange(cols)
        if not increasing:
            powers = powers[::-1]
        return v[:, None] ** powers[None, :]

    return op(fn, x, op_name="vander")


def nanmedian(x, axis=None, keepdim=False, name=None):
    def fn(v):
        return jnp.nanmedian(v, axis=axis, keepdims=keepdim)

    return op(fn, x, op_name="nanmedian")


def polygamma(x, n, name=None):
    def fn(v):
        from jax.scipy.special import polygamma as _pg

        return _pg(n, v)

    return op(fn, x, op_name="polygamma")


def i0(x, name=None):
    def fn(v):
        from jax.scipy.special import i0 as _i0

        return _i0(v)

    return op(fn, x, op_name="i0")


def i0e(x, name=None):
    def fn(v):
        from jax.scipy.special import i0e as _i0e

        return _i0e(v)

    return op(fn, x, op_name="i0e")


def positive(x, name=None):
    return op(lambda v: +v, x, op_name="positive")


def negative(x, name=None):
    return op(jnp.negative, x, op_name="negative")


def conj_physical(x, name=None):
    return op(jnp.conj, x, op_name="conj_physical")


def ldexp(x, y, name=None):
    return op(lambda a, b: a * jnp.exp2(b.astype(jnp.float32)).astype(
        a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32),
        x, y, op_name="ldexp")


def hypot(x, y, name=None):
    return op(jnp.hypot, x, y, op_name="hypot")


def signbit(x, name=None):
    return op(jnp.signbit, x, op_name="signbit")


def isreal(x, name=None):
    return op(jnp.isreal, x, op_name="isreal")


def isposinf(x, name=None):
    return op(jnp.isposinf, x, op_name="isposinf")


def isneginf(x, name=None):
    return op(jnp.isneginf, x, op_name="isneginf")


def broadcast_shape(x_shape, y_shape):
    import numpy as _np

    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def add_n(inputs, name=None):
    """Elementwise sum of a tensor list (reference: sum_op.cc, exposed as
    paddle.add_n)."""
    if isinstance(inputs, Tensor):
        return inputs.clone()
    seq = list(inputs)
    if not seq:
        raise ValueError("add_n expects at least one input")

    def fn(*vals):
        out = vals[0]
        for v in vals[1:]:
            out = out + v
        return out

    return op(fn, *seq, op_name="add_n")


def tanh_(x, name=None):
    """Inplace tanh (reference: tanh_ activation inplace variant)."""
    x._replace_from(tanh(x))
    return x

"""paddle_tpu.tensor — functional op namespace + Tensor method patching.

Reference: python/paddle/tensor/__init__.py plus the monkey-patch machinery in
fluid/dygraph/{varbase_patch_methods.py,math_op_patch.py} that attaches ~300
methods and operator dunders onto the Tensor type.
"""
from __future__ import annotations

from ..framework.tensor import Tensor

from .attribute import is_complex, is_floating_point, is_integer, rank, shape  # noqa: F401
from .creation import (  # noqa: F401
    arange, assign, clone, complex, diag, diagflat, empty, empty_like, eye, full,
    full_like, imag, linspace, logspace, meshgrid, numel, ones, ones_like, real,
    to_tensor, tril, triu, zeros, zeros_like,
)
from .einsum import einsum  # noqa: F401
from .linalg import (  # noqa: F401
    bincount, bmm, cholesky, cholesky_solve, cond, corrcoef, cov, cross, det, dist,
    dot, eig, eigh, eigvals, eigvalsh, histogram, inv, lstsq, lu, matmul,
    matrix_power, matrix_rank, mm, multi_dot, mv, norm, pinv, qr, slogdet, solve,
    svd, triangular_solve,
)
from .logic import (  # noqa: F401
    allclose, bitwise_and, bitwise_not, bitwise_or, bitwise_xor, equal, equal_all,
    greater_equal, greater_than, is_empty, is_tensor, isclose, less_equal,
    less_than, logical_and, logical_not, logical_or, logical_xor, not_equal,
)
from .manipulation import (  # noqa: F401
    as_complex, as_real, atleast_1d, atleast_2d, atleast_3d, broadcast_tensors,
    broadcast_to, cast, chunk, concat, expand, expand_as, flatten, flip, gather,
    gather_nd, index_sample, index_select, masked_fill, masked_select, moveaxis,
    put_along_axis, repeat_interleave, reshape, reshape_, roll, rot90, scatter,
    scatter_, scatter_nd, scatter_nd_add, slice, split, squeeze, squeeze_, stack,
    strided_slice, swapaxes, t, take_along_axis, tensordot, tile, transpose,
    unbind, unique, unique_consecutive, unsqueeze, unsqueeze_, unstack, view,
    unflatten, as_strided, tensor_split, hsplit, vsplit, dsplit,
    hstack, vstack, dstack, column_stack, row_stack, crop, index_add,
    index_put, masked_scatter, reverse, diagonal, multiplex, shard_index,
    fill_diagonal, fill_diagonal_, shuffle_batch, partial_concat,
    partial_sum, pad_constant_like,
)
from .math import (  # noqa: F401
    add_n, tanh_,
    abs, acos, acosh, add, add_, addmm, all, amax, amin, angle, any, asin, asinh,
    atan, atan2, atanh, ceil, clip, clip_, conj, copysign, cos, cosh,
    count_nonzero, cummax, cummin, cumprod, cumsum, deg2rad, diff, digamma,
    divide, divide_, erf, erfinv, exp, expm1, floor, floor_divide, floor_mod,
    fmax, fmin, frac, gcd, heaviside, increment, inner, isfinite, isinf, isnan,
    kron, lcm, lerp, lgamma, log, log1p, log2, log10, logaddexp, logit,
    logsumexp, max, maximum, mean, median, min, minimum, mod, multiply,
    multiply_, nan_to_num, nanmean, nansum, neg, nextafter, outer, pow, prod,
    quantile, rad2deg, reciprocal, remainder, round, rsqrt, scale, scale_,
    sigmoid, sign, sin, sinh, sqrt, square, stanh, std, subtract, subtract_,
    sum, tan, tanh, trace, trunc, var,
    cdist, take, logcumsumexp, renorm, frexp, trapezoid,
    cumulative_trapezoid, vander, nanmedian, polygamma, i0, i0e,
    positive, negative, conj_physical, ldexp, hypot, signbit, isreal,
    isposinf, isneginf, broadcast_shape,
)
from .random import (  # noqa: F401
    bernoulli, exponential_, multinomial, normal, normal_, poisson, rand,
    rand_like, randint, randint_like, randn, randn_like, randperm,
    standard_normal, uniform, uniform_,
)
from .search import (  # noqa: F401
    argmax, argmin, argsort, bucketize, kthvalue, mode, nonzero, searchsorted,
    sort, topk, where, where_,
)

import builtins as _bi

# ------------------------------------------------------------------ patching
_METHODS = dict(
    # math
    abs=abs, acos=acos, acosh=acosh, add=add, add_=add_, addmm=addmm, all=all,
    amax=amax, amin=amin, angle=angle, any=any, asin=asin, asinh=asinh, atan=atan,
    atanh=atanh, ceil=ceil, clip=clip, clip_=clip_, conj=conj, cos=cos, cosh=cosh,
    count_nonzero=count_nonzero, cumprod=cumprod, cumsum=cumsum, digamma=digamma,
    divide=divide, divide_=divide_, erf=erf, erfinv=erfinv, exp=exp, expm1=expm1,
    floor=floor, floor_divide=floor_divide, floor_mod=floor_mod, fmax=fmax,
    fmin=fmin, frac=frac, inner=inner, isfinite=isfinite, isinf=isinf,
    isnan=isnan, kron=kron, lerp=lerp, lgamma=lgamma, log=log, log1p=log1p,
    log2=log2, log10=log10, logit=logit, logsumexp=logsumexp, max=max,
    maximum=maximum, mean=mean, median=median, min=min, minimum=minimum, mod=mod,
    multiply=multiply, multiply_=multiply_, nan_to_num=nan_to_num, nanmean=nanmean,
    nansum=nansum, neg=neg, outer=outer, pow=pow, prod=prod, quantile=quantile,
    reciprocal=reciprocal, remainder=remainder, round=round, rsqrt=rsqrt,
    scale=scale, scale_=scale_, sigmoid=sigmoid, sign=sign, sin=sin, sinh=sinh,
    sqrt=sqrt, square=square, std=std, subtract=subtract, subtract_=subtract_,
    sum=sum, tan=tan, tanh=tanh, trace=trace, trunc=trunc, var=var,
    # linalg
    bincount=bincount, bmm=bmm, cholesky=cholesky, cross=cross, det=det,
    dist=dist, dot=dot, eigvals=eigvals, histogram=histogram, inverse=inv,
    matmul=matmul, matrix_power=matrix_power, mm=mm, mv=mv, norm=norm, qr=qr,
    # logic
    allclose=allclose, bitwise_and=bitwise_and, bitwise_not=bitwise_not,
    bitwise_or=bitwise_or, bitwise_xor=bitwise_xor, equal=equal,
    equal_all=equal_all, greater_equal=greater_equal, greater_than=greater_than,
    isclose=isclose, less_equal=less_equal, less_than=less_than,
    logical_and=logical_and, logical_not=logical_not, logical_or=logical_or,
    logical_xor=logical_xor, not_equal=not_equal,
    # manipulation
    broadcast_to=broadcast_to, chunk=chunk, expand=expand, expand_as=expand_as,
    flatten=flatten, flip=flip, gather=gather, gather_nd=gather_nd,
    index_sample=index_sample, index_select=index_select, masked_fill=masked_fill,
    masked_select=masked_select, moveaxis=moveaxis,
    repeat_interleave=repeat_interleave, reshape=reshape, reshape_=reshape_,
    roll=roll, rot90=rot90, scatter=scatter, scatter_=scatter_,
    scatter_nd_add=scatter_nd_add, slice=slice, split=split, squeeze=squeeze,
    squeeze_=squeeze_, strided_slice=strided_slice, swapaxes=swapaxes,
    take_along_axis=take_along_axis, tile=tile, transpose=transpose,
    unbind=unbind, unique=unique, unsqueeze=unsqueeze, unsqueeze_=unsqueeze_,
    unstack=unstack,
    # search
    argmax=argmax, argmin=argmin, argsort=argsort, kthvalue=kthvalue,
    nonzero=nonzero, sort=sort, topk=topk, where=where,
    # random
    bernoulli=bernoulli, exponential_=exponential_, multinomial=multinomial,
    normal_=normal_, uniform_=uniform_,
    # remaining reference Tensor-method surface (concat/stack take lists,
    # not methods, matching the reference)
    diag=diag, t=t, tril=tril, triu=triu,
)


def _make_method(fn):
    def method(self, *args, **kwargs):
        return fn(self, *args, **kwargs)

    method.__name__ = fn.__name__
    return method


for _name, _fn in _METHODS.items():
    setattr(Tensor, _name, _make_method(_fn))


def _binop(fn, reflexive=False):
    if reflexive:
        def method(self, other):
            return fn(other, self)
    else:
        def method(self, other):
            return fn(self, other)
    return method


Tensor.__add__ = _binop(add)
Tensor.__radd__ = _binop(add, True)
Tensor.__sub__ = _binop(subtract)
Tensor.__rsub__ = _binop(subtract, True)
Tensor.__mul__ = _binop(multiply)
Tensor.__rmul__ = _binop(multiply, True)
Tensor.__truediv__ = _binop(divide)
Tensor.__rtruediv__ = _binop(divide, True)
Tensor.__floordiv__ = _binop(floor_divide)
Tensor.__rfloordiv__ = _binop(floor_divide, True)
Tensor.__mod__ = _binop(remainder)
Tensor.__pow__ = _binop(pow)
Tensor.__rpow__ = _binop(pow, True)
Tensor.__matmul__ = _binop(matmul)
Tensor.__rmatmul__ = _binop(matmul, True)
Tensor.__neg__ = lambda self: neg(self)
Tensor.__abs__ = lambda self: abs(self)
Tensor.__invert__ = lambda self: logical_not(self)
Tensor.__eq__ = _binop(equal)
Tensor.__ne__ = _binop(not_equal)
Tensor.__lt__ = _binop(less_than)
Tensor.__le__ = _binop(less_equal)
Tensor.__gt__ = _binop(greater_than)
Tensor.__ge__ = _binop(greater_equal)
Tensor.__and__ = _binop(logical_and)
Tensor.__or__ = _binop(logical_or)
Tensor.__xor__ = _binop(logical_xor)
Tensor.__hash__ = lambda self: id(self)

__all__ = [n for n in dir() if not n.startswith("_")]

"""Search / sort ops (reference: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._helpers import Tensor, as_tensor, op, val


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def fn(v):
        if axis is None:
            out = jnp.argmax(v.reshape(-1))
            return out.reshape((1,) * v.ndim).astype(dtype) if keepdim else out.astype(dtype)
        out = jnp.argmax(v, axis=axis)
        if keepdim:
            out = jnp.expand_dims(out, axis)
        return out.astype(dtype)

    return op(fn, x, op_name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def fn(v):
        if axis is None:
            out = jnp.argmin(v.reshape(-1))
            return out.reshape((1,) * v.ndim).astype(dtype) if keepdim else out.astype(dtype)
        out = jnp.argmin(v, axis=axis)
        if keepdim:
            out = jnp.expand_dims(out, axis)
        return out.astype(dtype)

    return op(fn, x, op_name="argmin")


def argsort(x, axis=-1, descending=False, name=None):
    def fn(v):
        idx = jnp.argsort(v, axis=axis, descending=descending)
        return idx.astype("int64")

    return op(fn, x, op_name="argsort")


def sort(x, axis=-1, descending=False, name=None):
    def fn(v):
        out = jnp.sort(v, axis=axis, descending=descending)
        return out

    return op(fn, x, op_name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    k = int(val(k))
    ax = axis if axis is not None else -1

    def fn(v):
        vv = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vv, k)
        else:
            vals, idx = jax.lax.top_k(-vv, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype("int64"), -1, ax)

    return op(fn, x, op_name="topk")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    x = as_tensor(x, y if isinstance(y, Tensor) else None)
    y = as_tensor(y, x)
    return op(lambda c, a, b: jnp.where(c, a, b), condition, x, y, op_name="where")


def where_(condition, x=None, y=None, name=None):
    out = where(condition, x, y)
    x._replace_from(out)
    return x


def nonzero(x, as_tuple=False):
    # dynamic output shape → host-side eager
    idx = np.nonzero(np.asarray(x.numpy()))
    if as_tuple:
        return tuple(Tensor(i.astype(np.int64)) for i in idx)
    return Tensor(np.stack(idx, axis=1).astype(np.int64))


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as ms

    return ms(x, mask)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"

    def fn(s, v):
        out = jnp.searchsorted(s, v, side=side)
        return out.astype("int32" if out_int32 else "int64")

    return op(fn, sorted_sequence, values, op_name="searchsorted")


def index_sample(x, index):
    from .manipulation import index_sample as f

    return f(x, index)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(v):
        vals = jnp.sort(v, axis=axis)
        idx = jnp.argsort(v, axis=axis).astype("int64")
        sl = [slice(None)] * v.ndim
        sl[axis] = slice(k - 1, k)
        out_v = vals[tuple(sl)]
        out_i = idx[tuple(sl)]
        if not keepdim:
            out_v = jnp.squeeze(out_v, axis=axis)
            out_i = jnp.squeeze(out_i, axis=axis)
        return out_v, out_i

    return op(fn, x, op_name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    arr = x.numpy()
    arr_m = np.moveaxis(arr, axis, -1)
    flat = arr_m.reshape(-1, arr_m.shape[-1])
    vals = np.empty(flat.shape[0], dtype=arr.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts)]
        vals[i] = best
        idxs[i] = np.where(row == best)[0][-1]
    shape = arr_m.shape[:-1]
    v = vals.reshape(shape)
    i = idxs.reshape(shape)
    if keepdim:
        v = np.expand_dims(v, axis)
        i = np.expand_dims(i, axis)
    return Tensor(v), Tensor(i)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)

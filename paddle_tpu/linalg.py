"""paddle.linalg namespace (parity: python/paddle/linalg.py re-exports)."""
from .tensor.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, eig, eigh, eigvals,
    eigvalsh, inv, lstsq, lu, matrix_power, matrix_rank, multi_dot, norm,
    pinv, qr, slogdet, solve, svd, triangular_solve,
    householder_product, lu_unpack, matrix_exp, matrix_norm,
    pca_lowrank, svd_lowrank, vector_norm,
)
